"""SLO-driven control plane [ISSUE 11]: FleetController knobs,
hysteresis/rate-limit/budget discipline, typed throttling, deadline
reaper, mesh resize, slope promotion, doctor attribution, and the
chaos-style scenario suite (controlled fleet defends the SLO an
uncontrolled twin breaches, with per-tenant wins2 bit-identical to
independents through every actuation).

The scenario harness is deterministic: SLO evaluations are pumped
manually (``SloMonitor.observe`` with an explicit clock), backlog is
built by wedging the batcher behind one large insert, and bursts are
interleaved with observations — no reliance on thread scheduling for
the control decisions themselves.
"""

import dataclasses
import json
import time

import numpy as np
import pytest

from tuplewise_tpu.obs.slo import SloMonitor
from tuplewise_tpu.serving import (
    BackpressureError,
    ControllerConfig,
    DeadlineExceededError,
    ExactAucIndex,
    FleetController,
    MicroBatchEngine,
    MultiTenantEngine,
    ServingConfig,
    TenancyConfig,
    TenantFleetIndex,
    TenantThrottledError,
)
from tuplewise_tpu.serving.control import ControllerSpecError, _Knob

SAT_SPEC = {"objectives": [
    {"name": "queue_sat", "type": "saturation",
     "metric": "queue_depth_live", "capacity": "queue_size",
     "max_fraction": 0.8},
    {"name": "no_hard_rejects", "type": "counter_max",
     "metric": "rejected_total", "max": 0},
]}

FAST_CTL = {"cooldown_s": 0.0, "up_ticks": 1, "down_ticks": 2}


def _observe(mon, eng, ts):
    mon.observe(eng.metrics.snapshot(), ts)


# --------------------------------------------------------------------- #
# spec + knob discipline                                                 #
# --------------------------------------------------------------------- #

class TestControllerSpec:
    def test_defaults_and_json_roundtrip(self):
        cfg = ControllerConfig.from_spec(None)
        assert cfg.enabled and set(cfg.knobs) == {
            "shed", "flush", "weights", "mesh", "promote"}
        cfg2 = ControllerConfig.from_spec(
            json.dumps({"knobs": ["shed"], "cooldown_s": 1.5}))
        assert cfg2.knobs == ("shed",) and cfg2.cooldown_s == 1.5

    def test_unknown_field_rejected(self):
        with pytest.raises(ControllerSpecError):
            ControllerConfig.from_spec({"coolness": 11})
        with pytest.raises(ControllerSpecError):
            ControllerConfig.from_spec({"knobs": ["turbo"]})

    def test_at_file(self, tmp_path):
        p = tmp_path / "ctl.json"
        p.write_text(json.dumps({"throttle_s": 0.25}))
        assert ControllerConfig.from_spec(
            "@" + str(p)).throttle_s == 0.25


class TestKnobDiscipline:
    def test_hysteresis_needs_consecutive_pressure(self):
        k = _Knob("x", cooldown_s=0.0, budget=100, up_ticks=3,
                  down_ticks=2, max_level=5)
        t = 0.0
        # interrupted streaks never actuate
        for want in (1, 1, 0, 1, 1, None, 1, 1):
            assert k.tick(want, t) == 0
            t += 1.0
        assert k.tick(1, t) == 1     # third consecutive
        assert k.level == 1

    def test_cooldown_rate_limits(self):
        k = _Knob("x", cooldown_s=1.0, budget=100, up_ticks=1,
                  down_ticks=1, max_level=100)
        steps = sum(abs(k.tick(1, 0.1 * i)) for i in range(100))
        # 9.9 simulated seconds / 1 s cooldown -> at most 10 steps
        assert steps <= 10

    def test_budget_bounds_pressured_steps_but_not_homecoming(self):
        k = _Knob("x", cooldown_s=0.0, budget=3, up_ticks=1,
                  down_ticks=1, max_level=100)
        t = 0.0
        ups = 0
        for _ in range(50):
            ups += max(0, k.tick(1, t))
            t += 1.0
        assert ups == 3 and k.used == 3
        downs = 0
        for _ in range(50):
            downs += -min(0, k.tick(0, t))
            t += 1.0
        assert downs == 3 and k.level == 0   # reverts ran budget-free

    def test_randomized_schedule_no_flap(self):
        rng = np.random.default_rng(7)
        k = _Knob("x", cooldown_s=0.5, budget=1000, up_ticks=2,
                  down_ticks=4, max_level=4, min_level=-2)
        t = 0.0
        moves = []
        for _ in range(500):
            want = int(rng.integers(-1, 2))
            s = k.tick(want, t)
            if s:
                moves.append(t)
            t += 0.05
        # rate limit: never two actuations inside one cooldown window
        assert all(b - a >= 0.5 for a, b in zip(moves, moves[1:]))
        # 25 simulated seconds / 0.5 cooldown -> hard per-window bound
        assert len(moves) <= 25 / 0.5 + 1
        assert -2 <= k.level <= 4


# --------------------------------------------------------------------- #
# typed throttling + per-tenant overrides                                #
# --------------------------------------------------------------------- #

class TestThrottle:
    def test_throttle_is_typed_expiring_and_counted(self):
        with MultiTenantEngine(ServingConfig(flush_timeout_s=0.001),
                               TenancyConfig()) as eng:
            eng.throttle_tenant("hot", retry_after_s=0.2)
            with pytest.raises(TenantThrottledError) as ei:
                eng.insert("hot", 1.0, 1)
            assert ei.value.tenant == "hot"
            assert 0 < ei.value.retry_after_s <= 0.2
            # other tenants unaffected
            assert eng.insert("calm", 1.0, 1).result(10.0) == 1
            time.sleep(0.25)
            assert eng.insert("hot", 1.0, 1).result(10.0) == 1
            m = eng.metrics.snapshot()
            assert m["tenant_throttled_total"]["value"] == 1
            assert m["tenant_throttled_total{tenant=hot}"]["value"] == 1
            kinds = [e["kind"] for e in eng.flight.events()]
            assert "tenant_throttled" in kinds

    def test_clear_throttles(self):
        with MultiTenantEngine(ServingConfig(),
                               TenancyConfig()) as eng:
            eng.throttle_tenant("a", 30.0)
            eng.throttle_tenant("b", 30.0)
            assert sorted(eng.throttled_tenants()) == ["a", "b"]
            assert eng.clear_throttles("a") == 1
            assert eng.clear_throttles() == 1
            assert eng.insert("a", 1.0, 1).result(10.0) == 1

    def test_weight_and_quota_overrides(self):
        with MultiTenantEngine(
                ServingConfig(flush_timeout_s=0.2, max_batch=64),
                TenancyConfig(tenant_quota=4, weight=2)) as eng:
            eng.set_tenant_quota("big", 64)
            # default quota would reject the 5th queued request; the
            # override admits far more
            futs = [eng.insert("big", float(i), i % 2)
                    for i in range(32)]
            for f in futs:
                f.result(10.0)
            eng.set_tenant_weight("big", 16)
            assert eng._tenant_weights["big"] == 16
            eng.set_tenant_weight("big", None)
            assert "big" not in eng._tenant_weights

    def test_controller_off_is_todays_behavior(self):
        """No controller: no throttles, no overrides, no controller
        metrics/flight kinds — the pre-ISSUE-11 engine, byte for
        byte."""
        scores, labels = (np.random.default_rng(3).standard_normal(200),
                          np.random.default_rng(4).random(200) < 0.5)
        with MultiTenantEngine(ServingConfig(flush_timeout_s=0.001),
                               TenancyConfig()) as eng:
            singles = {}
            for i in range(0, 200, 10):
                tid = f"t{(i // 10) % 4}"
                eng.insert(tid, scores[i:i + 10],
                           labels[i:i + 10]).result(10.0)
                singles.setdefault(tid, ExactAucIndex(
                    engine="jax")).insert_batch(scores[i:i + 10],
                                                labels[i:i + 10])
            eng.flush()
            assert not eng._throttles and not eng._tenant_weights \
                and not eng._tenant_quotas
            m = eng.metrics.snapshot()
            assert "controller_actuations_total" not in m
            assert m["tenant_throttled_total"]["value"] == 0
            assert not eng.flight.events("actuation")
            for tid, idx in singles.items():
                assert eng.fleet.wins2(tid) == idx._wins2


# --------------------------------------------------------------------- #
# deadline reaper [ISSUE 11 bugfix]                                      #
# --------------------------------------------------------------------- #

class TestDeadlineReaper:
    def test_wedged_batcher_expires_queued_requests(self):
        """Regression: dispatch-time expiry (engine.py) never runs
        while the batcher is wedged mid-apply — the timer must fail
        the rotting request typed, long before the wedge clears."""
        eng = MicroBatchEngine(ServingConfig(
            deadline_s=0.1, flush_timeout_s=0.001, max_batch=1))
        orig = eng.index.insert_batch

        def wedge(s, l):
            time.sleep(1.2)
            return orig(s, l)

        eng.index.insert_batch = wedge
        try:
            eng.insert(1.0, 1)          # dispatched, wedges the batcher
            time.sleep(0.05)
            t0 = time.perf_counter()
            f2 = eng.insert(2.0, 0)     # rots in the queue
            with pytest.raises(DeadlineExceededError):
                f2.result(timeout=0.8)
            waited = time.perf_counter() - t0
            # the old dispatch-only path could not fail it before the
            # wedge cleared at ~1.2 s
            assert waited < 0.8, waited
            assert eng.metrics.snapshot()[
                "deadline_expired_total"]["value"] >= 1
            assert any(e["kind"] == "deadline_expired"
                       for e in eng.flight.events())
        finally:
            eng.index.insert_batch = orig
            eng.close()

    def test_expiry_is_counted_once(self):
        """Reaper and dispatch both see a stale request — exactly one
        of them wins and the counter moves once per request."""
        eng = MicroBatchEngine(ServingConfig(
            deadline_s=0.05, flush_timeout_s=0.001, max_batch=1))
        orig = eng.index.insert_batch

        def wedge(s, l):
            time.sleep(0.4)
            return orig(s, l)

        eng.index.insert_batch = wedge
        try:
            eng.insert(1.0, 1)
            time.sleep(0.02)
            futs = [eng.insert(float(i), i % 2) for i in range(4)]
            for f in futs:
                with pytest.raises(DeadlineExceededError):
                    f.result(timeout=1.0)
            time.sleep(0.5)     # let the wedge clear + batcher drain
            assert eng.metrics.snapshot()[
                "deadline_expired_total"]["value"] == 4
        finally:
            eng.index.insert_batch = orig
            eng.close()

    def test_fleet_reaper_frees_quota(self):
        eng = MultiTenantEngine(
            ServingConfig(deadline_s=0.08, flush_timeout_s=0.001),
            TenancyConfig(tenant_quota=2))
        orig = eng.fleet.apply_inserts

        def wedge(items):
            time.sleep(0.6)
            return orig(items)

        eng.fleet.apply_inserts = wedge
        try:
            f0 = eng.insert("a", 1.0, 1)    # wedges the batcher
            time.sleep(0.02)
            f1 = eng.insert("b", 1.0, 1)
            f2 = eng.insert("b", 2.0, 0)    # quota full for b
            for f in (f1, f2):
                with pytest.raises(DeadlineExceededError):
                    f.result(timeout=1.0)
            # reaper REMOVED them: quota slots free again (submit
            # succeeds where the quota would have rejected); un-wedge
            # before the new request's own deadline can expire
            eng.fleet.apply_inserts = orig
            f0.result(timeout=5.0)
            f3 = eng.insert("b", 3.0, 1)
            assert f3.result(timeout=5.0) == 1
            assert eng.metrics.snapshot()[
                "deadline_expired_total"]["value"] == 2
        finally:
            eng.fleet.apply_inserts = orig
            eng.close()


# --------------------------------------------------------------------- #
# mesh resize                                                            #
# --------------------------------------------------------------------- #

class TestMeshResize:
    def test_resize_parity_grow_and_shrink(self):
        fleet = TenantFleetIndex(shards=2, compact_every=32)
        singles = {}
        rng = np.random.default_rng(5)

        def feed(k):
            items = []
            for t in range(6):
                s = rng.standard_normal(k)
                l = rng.random(k) < 0.5
                tid = f"t{t}"
                items.append((tid, s, l))
                singles.setdefault(tid, ExactAucIndex(
                    compact_every=32, engine="jax")).insert_batch(s, l)
            fleet.apply_inserts(items)

        feed(60)
        assert fleet.resize_shards(4)
        assert fleet.shards == 4
        feed(60)
        assert fleet.resize_shards(1)
        feed(60)
        assert not fleet.resize_shards(1)      # no-op width
        assert not fleet.resize_shards(1024)   # beyond the pool
        for tid, idx in singles.items():
            assert fleet.wins2(tid) == idx._wins2
            assert fleet.auc(tid) == idx.auc()
        m = fleet.metrics.snapshot()
        assert m["mesh_width"]["value"] == 1
        assert m["reshard_events"]["value"] >= 2
        fleet.close()

    def test_unsharded_fleet_refuses(self):
        fleet = TenantFleetIndex()
        assert not fleet.resize_shards(2)
        fleet.close()


# --------------------------------------------------------------------- #
# controller knobs end-to-end (deterministic pumping)                    #
# --------------------------------------------------------------------- #

class TestControllerKnobs:
    def test_flush_widen_and_restore(self):
        with MultiTenantEngine(
                ServingConfig(queue_size=64, flush_timeout_s=0.001,
                              max_batch=32),
                TenancyConfig()) as eng:
            mon = SloMonitor(SAT_SPEC, registry=eng.metrics,
                             flight=eng.flight,
                             context=dataclasses.asdict(eng.config))
            ctl = FleetController(
                eng, dict(FAST_CTL, knobs=["flush"])).attach(mon)
            t = 0.0
            eng.metrics.gauge("queue_depth_live").set(50)   # 0.78 sat
            _observe(mon, eng, t)
            assert eng.config.flush_timeout_s == 0.002
            assert eng.config.max_batch == 64
            eng.metrics.gauge("queue_depth_live").set(0)
            for i in range(3):
                _observe(mon, eng, t + 0.1 * (i + 1))
            assert eng.config.flush_timeout_s == 0.001
            assert eng.config.max_batch == 32
            acts = eng.flight.events("actuation")
            assert [a["action"] for a in acts] == ["widen", "restore"]
            assert all(a["signal"] for a in acts)
            assert ctl.state()["knobs"]["flush"]["level"] == 0

    def test_every_actuation_has_a_nonnull_signal(self):
        """Randomized signal schedule: bounded actuations per window,
        every actuation flight-evented with a non-null triggering
        signal."""
        rng = np.random.default_rng(11)
        with MultiTenantEngine(
                ServingConfig(queue_size=64, flush_timeout_s=0.001),
                TenancyConfig()) as eng:
            mon = SloMonitor(SAT_SPEC, registry=eng.metrics,
                             flight=eng.flight,
                             context=dataclasses.asdict(eng.config))
            FleetController(
                eng, {"cooldown_s": 0.05, "up_ticks": 2,
                      "down_ticks": 3}).attach(mon)
            t = 0.0
            for _ in range(300):
                eng.metrics.gauge("queue_depth_live").set(
                    int(rng.integers(0, 64)))
                _observe(mon, eng, t)
                t += 0.01
            acts = eng.flight.events("actuation")
            assert all(isinstance(a["signal"], dict) and a["signal"]
                       for a in acts)
            # 3 simulated seconds / 0.05 cooldown -> per-knob bound
            per_knob = {}
            for a in acts:
                per_knob[a["knob"]] = per_knob.get(a["knob"], 0) + 1
            assert all(n <= 3 / 0.05 + 1 for n in per_knob.values()), \
                per_knob
            assert mon.actuator_errors == 0

    def test_slope_promotion_fires_before_threshold(self):
        with MultiTenantEngine(
                ServingConfig(flush_timeout_s=0.001),
                TenancyConfig(whale_threshold=2000)) as eng:
            ctl = FleetController(
                eng, dict(FAST_CTL, knobs=["promote"],
                          promote_lookahead_s=2.0))
            rng = np.random.default_rng(2)
            s = rng.standard_normal(300)
            l = rng.random(300) < 0.5
            eng.insert("hot", s, l).result(10.0)
            eng.flush()
            sig = lambda t: {"ts_mono": t,  # noqa: E731
                             "metrics": eng.metrics.snapshot(),
                             "transitions": [], "objectives": {}}
            ctl.on_signals(sig(0.0))
            s2 = rng.standard_normal(400)
            l2 = rng.random(400) < 0.5
            eng.insert("hot", s2, l2).result(10.0)
            eng.flush()
            # rate = 400 events / 0.1 s -> projected 700 + 8000 > 2000
            ctl.on_signals(sig(0.1))
            assert eng.fleet.is_whale("hot")
            acts = eng.flight.events("actuation")
            assert any(a["action"] == "promote_whale"
                       and a["signal"]["tenant"] == "hot"
                       and a["signal"]["value"] > 0 for a in acts)
            # promotion is statistically invisible [PR 9 contract]
            oracle = ExactAucIndex(engine="jax")
            oracle.insert_batch(np.concatenate([s, s2]),
                                np.concatenate([l, l2]))
            assert eng.fleet.wins2("hot") == oracle._wins2

    def test_weights_boost_and_restore(self):
        with MultiTenantEngine(
                ServingConfig(flush_timeout_s=0.001),
                TenancyConfig(weight=2)) as eng:
            ctl = FleetController(
                eng, dict(FAST_CTL, knobs=["weights"], slow_factor=2.0))
            m = eng.metrics
            for i, tid in enumerate(["a", "b", "c", "d", "slowpoke"]):
                h = m.histogram("insert_latency_s",
                                labels={"tenant": tid})
                v = 0.5 if tid == "slowpoke" else 0.01
                for _ in range(10):
                    h.observe(v)
            sig = lambda t: {"ts_mono": t,  # noqa: E731
                             "metrics": m.snapshot(),
                             "transitions": [], "objectives": {}}
            ctl.on_signals(sig(0.0))
            assert eng._tenant_weights.get("slowpoke") == 2 * 4
            # calm: slowpoke's p99 falls back under the factor once
            # fast samples dominate its retained window -> restore
            h = m.histogram("insert_latency_s",
                            labels={"tenant": "slowpoke"})
            for _ in range(3000):
                h.observe(0.01)
            for t in range(1, 4):
                ctl.on_signals(sig(0.1 * t))
            assert "slowpoke" not in eng._tenant_weights
            acts = eng.flight.events("actuation")
            assert [a["action"] for a in acts] == ["boost", "restore"]


# --------------------------------------------------------------------- #
# scenario suite [ISSUE 11 acceptance]                                   #
# --------------------------------------------------------------------- #

def _run_flash_crowd(controlled, tenants=16, rounds=6, burst=80,
                     shards=None, chaos=None, whale="t0",
                     mesh_knob=False):
    """One flash-crowd run: per round, a large innocent insert wedges
    the batcher while ``whale`` bursts ``burst`` single-event inserts;
    the SLO monitor is pumped every 20 submits. Returns (slo_report,
    per-tenant wins2 of the fleet, independent-oracle wins2 over the
    ADMITTED events, metrics snapshot, engine flight events)."""
    rng = np.random.default_rng(17)
    cfg = ServingConfig(queue_size=64, policy="reject",
                        flush_timeout_s=0.001, max_batch=32,
                        mesh_shards=shards)
    knobs = ["shed", "flush"] + (["mesh"] if mesh_knob else [])
    injector = None
    if chaos is not None:
        from tuplewise_tpu.testing.chaos import FaultInjector

        injector = FaultInjector.from_spec(chaos)
    # admitted events per tenant, oracled AFTER the run (a jitted
    # index insert per submit would distort the burst timing the
    # scenario depends on)
    admitted = {}

    def feed_single(tid, s, l):
        admitted.setdefault(tid, []).append((s, l))

    with MultiTenantEngine(cfg, TenancyConfig(
            max_tenants=tenants + 8, tenant_quota=4096),
            chaos=injector) as eng:
        mon = SloMonitor(SAT_SPEC, registry=eng.metrics,
                         flight=eng.flight,
                         context=dataclasses.asdict(cfg))
        if controlled:
            FleetController(
                eng, dict(FAST_CTL, knobs=knobs,
                          mesh_up_ticks=1, mesh_down_ticks=64,
                          throttle_s=0.05)).attach(mon)
        for r in range(rounds):
            # innocents: small batches, resolved in bounded windows
            # (in-quota, polite — they never outrun the queue)
            futs = []

            def _drain():
                for tid_, s_, l_, f_ in futs:
                    f_.result(30.0)
                    feed_single(tid_, s_, l_)
                futs.clear()

            for k in range(1, tenants):
                s = rng.standard_normal(8)
                l = rng.random(8) < 0.5
                futs.append((f"t{k}", s, l,
                             eng.insert(f"t{k}", s, l)))
                if len(futs) >= 32:
                    _drain()
            _drain()
            # the wedge: one big innocent insert occupies the batcher
            ws = rng.standard_normal(30_000)
            wl = rng.random(30_000) < 0.5
            wedge_fut = eng.insert(f"t{tenants - 1}", ws, wl)
            feed_single(f"t{tenants - 1}", ws, wl)
            # the flash crowd: whale bursts while the batcher is busy
            for i in range(burst):
                s = rng.standard_normal(1)
                l = rng.random(1) < 0.5
                try:
                    eng.insert(whale, s, l)
                    feed_single(whale, s, l)
                except TenantThrottledError:
                    pass    # controlled shed: excluded from oracle too
                except BackpressureError:
                    pass    # the uncontrolled twin's hard rejects
                # every 10 submits: the queue must not be able to jump
                # from below the warn band (0.7*0.8*64 = 36) past the
                # breach line (0.8*64 = 51) between two observations
                if (i + 1) % 10 == 0:
                    _observe(mon, eng, time.perf_counter())
            wedge_fut.result(60.0)
            eng.flush()
            _observe(mon, eng, time.perf_counter())
            time.sleep(0.06)    # let throttles expire between rounds
        eng.flush()
        slo = mon.report()
        m = eng.metrics.snapshot()
        fleet_wins = {t: eng.fleet.wins2(t)
                      for t in eng.fleet.tenants()}
        flight = eng.flight.events()
    oracle_wins = {}
    for tid, batches in admitted.items():
        idx = ExactAucIndex(engine="jax")
        idx.insert_batch(np.concatenate([s for s, _ in batches]),
                         np.concatenate([l for _, l in batches]))
        oracle_wins[tid] = idx._wins2
    return slo, fleet_wins, oracle_wins, m, flight


class TestScenarios:
    def test_flash_crowd_controlled_vs_uncontrolled(self):
        """[acceptance] the controlled fleet keeps the SLO verdict
        healthy and sheds ONLY the flooding tenant (typed, zero hard
        rejects); the uncontrolled twin breaches. Per-tenant wins2
        stays bit-identical to independents through every actuation."""
        slo, fleet_wins, oracle_wins, m, flight = _run_flash_crowd(
            controlled=True)
        assert slo["healthy"], slo
        assert m["rejected_total"]["value"] == 0
        assert m["tenant_rejected_total"]["value"] == 0
        assert m["tenant_throttled_total"]["value"] > 0
        # shed/throttle affects admission, never applied state
        assert fleet_wins == oracle_wins
        acts = [e for e in flight if e["kind"] == "actuation"]
        assert acts and all(a["signal"] for a in acts)
        throttled = [a for a in acts if a["action"] == "throttle"]
        assert throttled
        assert all(set(a["tenants"]) == {"t0"} for a in throttled)

        slo_u, fleet_u, oracle_u, m_u, _ = _run_flash_crowd(
            controlled=False)
        assert not slo_u["healthy"], "uncontrolled twin must breach"
        assert fleet_u == oracle_u   # parity holds even while breaching

    def test_tenant_ramp_controlled_vs_uncontrolled(self):
        """[acceptance] onboarding ramp: each arriving tenant bursts;
        the controller throttles the arrival spike so the shared queue
        never saturates and nobody gets a hard reject."""
        for controlled in (True, False):
            rng = np.random.default_rng(23)
            # max_batch=8 keeps the breach deterministic [ISSUE 14]:
            # at 32, the batcher's first pickup could absorb most of
            # an arrival's 60-request burst alongside the wedge, and
            # with warm jit caches the observed depth never crossed
            # the 0.8 saturation line — the uncontrolled twin then
            # read healthy by luck of DRR timing (seed-reproducible
            # flake). Small drains can't hide a 60-deep burst.
            cfg = ServingConfig(queue_size=64, policy="reject",
                                flush_timeout_s=0.001, max_batch=8)
            admitted = {}
            with MultiTenantEngine(cfg, TenancyConfig(
                    max_tenants=128, tenant_quota=4096)) as eng:
                mon = SloMonitor(SAT_SPEC, registry=eng.metrics,
                                 flight=eng.flight,
                                 context=dataclasses.asdict(cfg))
                if controlled:
                    FleetController(
                        eng, dict(FAST_CTL, knobs=["shed", "flush"],
                                  throttle_s=0.05)).attach(mon)
                for arrival in range(8):
                    ws = rng.standard_normal(30_000)
                    wl = rng.random(30_000) < 0.5
                    wedge = eng.insert("base", ws, wl)
                    admitted.setdefault("base", []).append((ws, wl))
                    # let the batcher claim the wedge ALONE before the
                    # burst: its long apply wave is what the arrival
                    # spike piles up behind [ISSUE 14 determinism]
                    time.sleep(0.005)
                    tid = f"new{arrival}"
                    for i in range(60):
                        s = rng.standard_normal(1)
                        l = rng.random(1) < 0.5
                        try:
                            eng.insert(tid, s, l)
                            admitted.setdefault(tid, []).append((s, l))
                        except TenantThrottledError:
                            pass
                        except BackpressureError:
                            pass    # uncontrolled twin's hard rejects
                        if (i + 1) % 10 == 0:
                            _observe(mon, eng, time.perf_counter())
                    wedge.result(60.0)
                    eng.flush()
                    _observe(mon, eng, time.perf_counter())
                    time.sleep(0.06)
                slo = mon.report()
                m = eng.metrics.snapshot()
                wins = {t: eng.fleet.wins2(t)
                        for t in eng.fleet.tenants()}
            oracle = {}
            for tid, batches in admitted.items():
                idx = ExactAucIndex(engine="jax")
                idx.insert_batch(
                    np.concatenate([s for s, _ in batches]),
                    np.concatenate([l for _, l in batches]))
                oracle[tid] = idx._wins2
            assert wins == oracle
            if controlled:
                assert slo["healthy"], slo
                assert m["rejected_total"]["value"] == 0
                assert m["tenant_throttled_total"]["value"] > 0
            else:
                assert not slo["healthy"], \
                    "uncontrolled ramp must breach"

    def test_device_loss_heals_then_controller_regrows(self):
        """[acceptance] device loss at S=2: the fleet heals (shrinks)
        through the PR 3/8 machinery, then the controller grows the
        mesh back under pressure — results bit-identical throughout."""
        chaos = {"faults": [{"point": "sharded_count", "on_call": 3,
                             "action": "error", "dropped": [1]}]}
        slo, fleet_wins, oracle_wins, m, flight = _run_flash_crowd(
            controlled=True, tenants=8, rounds=4, shards=2,
            chaos=chaos, mesh_knob=True)
        assert slo["healthy"], slo
        assert fleet_wins == oracle_wins
        kinds = [e["kind"] for e in flight]
        assert "heal" in kinds          # the loss was healed
        grows = [e for e in flight if e["kind"] == "actuation"
                 and e["knob"] == "mesh" and e["action"] == "grow"]
        assert grows and all(a["signal"] for a in grows)
        assert m["mesh_width"]["value"] > 1

    @pytest.mark.slow
    def test_flash_crowd_t256(self):
        """[acceptance, slow] the headline scale: T=256 over S=2."""
        slo, fleet_wins, oracle_wins, m, flight = _run_flash_crowd(
            controlled=True, tenants=256, rounds=3, shards=2)
        assert slo["healthy"], slo
        assert m["rejected_total"]["value"] == 0
        assert fleet_wins == oracle_wins
        slo_u, fleet_u, oracle_u, _, _ = _run_flash_crowd(
            controlled=False, tenants=256, rounds=3, shards=2)
        assert not slo_u["healthy"]
        assert fleet_u == oracle_u


# --------------------------------------------------------------------- #
# doctor attribution [ISSUE 11 satellite]                                #
# --------------------------------------------------------------------- #

class TestDoctorActuations:
    def _artifacts(self, tmp_path, events, rows_after=True):
        from tuplewise_tpu.obs.flight import FlightRecorder

        fr = FlightRecorder()
        for kind, fields in events:
            fr.record(kind, **fields)
        fpath = str(tmp_path / "flight.jsonl")
        fr.dump_to(fpath)
        mpath = str(tmp_path / "metrics.jsonl")
        ts = time.perf_counter() + (100.0 if rows_after else -100.0)
        with open(mpath, "w") as f:
            for i in range(2):
                f.write(json.dumps({
                    "seq": i + 1, "ts_wall": time.time(),
                    "ts_mono": ts + i, "metrics": {}}) + "\n")
        return mpath, fpath

    def test_attributed_actuations_keep_verdict(self, tmp_path):
        from tuplewise_tpu.obs.doctor import diagnose

        mp, fp = self._artifacts(tmp_path, [
            ("actuation", dict(knob="shed", action="throttle",
                               signal={"objective": "queue_sat",
                                       "value": 0.7,
                                       "threshold": 0.8})),
            ("actuation", dict(knob="flush", action="widen",
                               signal={"objective": "queue_sat",
                                       "value": 0.75,
                                       "threshold": 0.8})),
        ])
        rep = diagnose(metrics_path=mp, flight_path=fp)
        assert rep["actuations"]["total"] == 2
        assert rep["actuations"]["attributed"] == 2
        assert rep["verdict"] == "healthy"
        assert rep["verdict_line"]["actuations_attributed"] == 2

    def test_missing_signal_downgrades(self, tmp_path):
        from tuplewise_tpu.obs.doctor import diagnose

        mp, fp = self._artifacts(tmp_path, [
            ("actuation", dict(knob="shed", action="throttle",
                               signal=None)),
        ])
        rep = diagnose(metrics_path=mp, flight_path=fp)
        assert rep["actuations"]["unattributed"] == 1
        assert rep["verdict"].startswith("degraded")
        assert "unattributed_actuation" in rep["verdict"]
        assert not rep["verdict_line"]["healthy"]

    def test_missing_effect_window_downgrades(self, tmp_path):
        from tuplewise_tpu.obs.doctor import diagnose

        mp, fp = self._artifacts(tmp_path, [
            ("actuation", dict(knob="mesh", action="grow",
                               signal={"objective": "x", "value": 1,
                                       "threshold": 2})),
        ], rows_after=False)
        rep = diagnose(metrics_path=mp, flight_path=fp)
        assert rep["actuations"]["unattributed"] == 1
        assert "unattributed_actuation" in rep["verdict"]

    def test_no_controller_no_actuation_block(self, tmp_path):
        from tuplewise_tpu.obs.doctor import diagnose

        mp, fp = self._artifacts(tmp_path, [
            ("compaction", dict(tier="minor")),
        ])
        rep = diagnose(metrics_path=mp, flight_path=fp)
        assert "actuations" not in rep
        assert rep["verdict_line"]["actuations"] == 0


# --------------------------------------------------------------------- #
# replay integration                                                     #
# --------------------------------------------------------------------- #

class TestReplayIntegration:
    def test_replay_fleet_with_controller(self):
        from tuplewise_tpu.serving import make_tenant_stream, replay_fleet

        scores, labels, tenants = make_tenant_stream(
            1500, 8, skew=1.2, seed=3)
        rec = replay_fleet(
            scores, labels, tenants, chunk=8, max_inflight=64,
            config=ServingConfig(flush_timeout_s=0.001),
            tenancy=TenancyConfig(max_tenants=16, tenant_quota=4096),
            slo_spec=SAT_SPEC,
            controller_spec={"knobs": ["shed", "flush"]})
        assert "controller" in rec
        assert rec["controller"]["enabled"]
        assert "events_tenant_throttled" in rec
        assert "tenant_throttled_total" in rec["admission"]
        assert rec["report"]["controller"]["actuations_total"] >= 0
        # unthrottled run: parity guardrail still applies
        assert rec["tenant_auc_max_abs_err"] < 1e-6

    def test_controller_needs_slo(self):
        from tuplewise_tpu.serving import make_tenant_stream, replay_fleet

        scores, labels, tenants = make_tenant_stream(50, 2, seed=0)
        with pytest.raises(ValueError, match="needs slo_spec"):
            replay_fleet(scores, labels, tenants,
                         controller_spec={})


class TestActuatorHook:
    def test_actuator_receives_objective_state(self):
        seen = []
        mon = SloMonitor(SAT_SPEC, context={"queue_size": 100},
                         actuators=[seen.append])
        mon.observe({"queue_depth_live": {"value": 90}}, 1.0)
        assert len(seen) == 1
        sig = seen[0]
        assert sig["ts_mono"] == 1.0
        assert sig["objectives"]["queue_sat"]["breached_now"]
        assert sig["objectives"]["queue_sat"]["value"] == 0.9

    def test_actuator_errors_are_swallowed_and_counted(self):
        def boom(sig):
            raise RuntimeError("actuator bug")

        mon = SloMonitor(SAT_SPEC, context={"queue_size": 100})
        mon.add_actuator(boom)
        mon.observe({}, 1.0)    # must not raise
        assert mon.actuator_errors == 1
        assert "actuator bug" in mon.last_actuator_error
