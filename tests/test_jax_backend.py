"""JAX single-device backend: oracle parity [SURVEY §5.1].

Complete statistics must match the NumPy oracle to float32 tolerance;
randomized schemes (different PRNG) must agree statistically.
"""

import numpy as np
import pytest

from tuplewise_tpu import Estimator
from tuplewise_tpu.data import make_gaussians


@pytest.fixture(scope="module")
def scores():
    X, Y = make_gaussians(3000, 2500, dim=1, separation=1.0, seed=7)
    return X[:, 0], Y[:, 0]


@pytest.fixture(scope="module")
def features():
    rng = np.random.default_rng(0)
    return rng.standard_normal((500, 4))


class TestCompleteParity:
    def test_auc(self, scores):
        s1, s2 = scores
        ref = Estimator("auc", backend="numpy").complete(s1, s2)
        got = Estimator("auc", backend="jax", tile_a=256, tile_b=256).complete(s1, s2)
        assert abs(got - ref) < 1e-6

    def test_auc_non_tile_multiple(self, scores):
        """Padding correctness: sizes not divisible by the tile."""
        s1, s2 = scores
        s1, s2 = s1[:1237], s2[:1019]
        ref = Estimator("auc", backend="numpy").complete(s1, s2)
        got = Estimator("auc", backend="jax", tile_a=256, tile_b=128).complete(s1, s2)
        assert abs(got - ref) < 1e-6

    def test_logistic(self, scores):
        s1, s2 = scores
        ref = Estimator("logistic", backend="numpy").complete(s1, s2)
        got = Estimator("logistic", backend="jax", tile_a=512, tile_b=512).complete(s1, s2)
        assert abs(got - ref) / abs(ref) < 1e-5

    def test_one_sample_scatter(self, features):
        ref = Estimator("scatter", backend="numpy").complete(features)
        got = Estimator("scatter", backend="jax", tile_a=128, tile_b=128).complete(features)
        assert abs(got - ref) / abs(ref) < 1e-5

    def test_triplet(self, features):
        X, Y = features[:60], features[60:100]
        ref = Estimator("triplet_indicator", backend="numpy").complete(X, Y)
        got = Estimator(
            "triplet_indicator", backend="jax", triplet_tile=32
        ).complete(X, Y)
        assert abs(got - ref) < 1e-6


class TestRandomizedSchemes:
    def test_local_average_unbiased(self, scores):
        s1, s2 = scores
        s1, s2 = s1[:400], s2[:400]
        est = Estimator("auc", backend="jax", n_workers=4,
                        tile_a=128, tile_b=128)
        u_n = est.complete(s1, s2)
        vals = [est.local_average(s1, s2, seed=m) for m in range(60)]
        se = np.std(vals) / np.sqrt(len(vals))
        assert abs(np.mean(vals) - u_n) < 4 * se + 1e-4

    def test_local_average_swr_one_sample_unbiased(self, features):
        A = features[:160]
        est = Estimator("scatter", backend="jax", n_workers=4,
                        tile_a=64, tile_b=64)
        u_n = est.complete(A)
        vals = [est.local_average(A, seed=m, scheme="swr") for m in range(120)]
        se = np.std(vals) / np.sqrt(len(vals))
        bias_if_broken = u_n / len(A)
        assert se < bias_if_broken  # enough power to notice gross bias
        assert abs(np.mean(vals) - u_n) < 4 * se

    def test_repartitioned_matches_complete_in_mean(self, scores):
        s1, s2 = scores
        s1, s2 = s1[:256], s2[:256]
        est = Estimator("auc", backend="jax", n_workers=4,
                        tile_a=64, tile_b=64)
        u_n = est.complete(s1, s2)
        vals = [
            est.repartitioned(s1, s2, n_rounds=4, seed=m) for m in range(40)
        ]
        se = np.std(vals) / np.sqrt(len(vals))
        assert abs(np.mean(vals) - u_n) < 4 * se + 1e-4

    def test_incomplete_unbiased(self, scores):
        s1, s2 = scores
        est = Estimator("auc", backend="jax", tile_a=256, tile_b=256)
        u_n = est.complete(s1, s2)
        vals = [
            est.incomplete(s1, s2, n_pairs=2000, seed=m) for m in range(100)
        ]
        se = np.std(vals) / np.sqrt(len(vals))
        assert abs(np.mean(vals) - u_n) < 4 * se + 1e-4

    def test_triplet_incomplete_unbiased(self, features):
        X, Y = features[:60], features[60:100]
        est = Estimator("triplet_indicator", backend="jax", triplet_tile=32)
        u_n = est.complete(X, Y)
        vals = [est.incomplete(X, Y, n_pairs=1000, seed=m) for m in range(80)]
        se = np.std(vals) / np.sqrt(len(vals))
        assert abs(np.mean(vals) - u_n) < 4 * se + 1e-3


class TestGradients:
    def test_pair_mean_grad_matches_dense(self):
        """jax.grad through the tiled (checkpointed) reduction equals the
        gradient of the dense O(n1*n2) computation."""
        import jax
        import jax.numpy as jnp

        from tuplewise_tpu.ops import pair_tiles
        from tuplewise_tpu.ops.kernels import logistic_kernel

        rng = np.random.default_rng(2)
        s1 = jnp.asarray(rng.standard_normal(75), jnp.float32)
        s2 = jnp.asarray(rng.standard_normal(53), jnp.float32)

        def tiled_loss(a, b):
            return pair_tiles.pair_mean(
                logistic_kernel, a, b, tile_a=32, tile_b=16
            )

        def dense_loss(a, b):
            d = a[:, None] - b[None, :]
            return jnp.mean(jnp.logaddexp(0.0, -d))

        g_tiled = jax.grad(tiled_loss, argnums=(0, 1))(s1, s2)
        g_dense = jax.grad(dense_loss, argnums=(0, 1))(s1, s2)
        np.testing.assert_allclose(g_tiled[0], g_dense[0], rtol=2e-5, atol=1e-7)
        np.testing.assert_allclose(g_tiled[1], g_dense[1], rtol=2e-5, atol=1e-7)
