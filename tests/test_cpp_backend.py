"""Native C++ backend parity vs the frozen NumPy oracle.

The cpp backend subclasses NumpyBackend and overrides only the innermost
pair reduction, so parity is EXACT for scheme structure (partitions come
from the same host RNG stream) and float-associativity-tight for values.
"""

import numpy as np
import pytest

from tuplewise_tpu import Estimator
from tuplewise_tpu.data import make_gaussians
from tuplewise_tpu.native import load_pair_lib

pytestmark = pytest.mark.skipif(
    load_pair_lib() is None, reason="no working g++ / native lib"
)


@pytest.fixture(scope="module")
def scores():
    X, Y = make_gaussians(1500, 1200, dim=1, separation=1.0, seed=5)
    return X[:, 0], Y[:, 0]


class TestDiffKernelParity:
    @pytest.mark.parametrize("kern", ["auc", "hinge", "logistic"])
    def test_complete(self, scores, kern):
        s1, s2 = scores
        ref = Estimator(kern, backend="numpy").complete(s1, s2)
        got = Estimator(kern, backend="cpp").complete(s1, s2)
        assert got == pytest.approx(ref, rel=1e-12)

    def test_local_average_same_partitions(self, scores):
        """Same host RNG stream -> identical partitions -> near-exact."""
        s1, s2 = scores
        ref = Estimator("auc", backend="numpy", n_workers=4)
        got = Estimator("auc", backend="cpp", n_workers=4)
        for seed in range(3):
            assert got.local_average(s1, s2, seed=seed) == pytest.approx(
                ref.local_average(s1, s2, seed=seed), rel=1e-12)

    def test_repartitioned(self, scores):
        s1, s2 = scores
        ref = Estimator("auc", backend="numpy", n_workers=4)
        got = Estimator("auc", backend="cpp", n_workers=4)
        assert got.repartitioned(s1, s2, n_rounds=3, seed=1) == pytest.approx(
            ref.repartitioned(s1, s2, n_rounds=3, seed=1), rel=1e-12)

    def test_incomplete(self, scores):
        """Sampling happens in the shared NumPy layer: identical draws."""
        s1, s2 = scores
        ref = Estimator("auc", backend="numpy").incomplete(
            s1, s2, n_pairs=2000, seed=2)
        got = Estimator("auc", backend="cpp").incomplete(
            s1, s2, n_pairs=2000, seed=2)
        assert got == pytest.approx(ref, rel=1e-12)


class TestOneSampleAndFallback:
    def test_scatter_with_ids(self):
        """One-sample scatter exercises the id-exclusion path in C++."""
        rng = np.random.default_rng(7)
        A = rng.standard_normal((400, 3))
        ref = Estimator("scatter", backend="numpy").complete(A)
        got = Estimator("scatter", backend="cpp").complete(A)
        assert got == pytest.approx(ref, rel=1e-12)

    def test_scatter_swr_duplicate_ids(self):
        """With-replacement partitions carry duplicate original ids;
        the C++ exclusion must match the oracle's id discipline."""
        rng = np.random.default_rng(8)
        A = rng.standard_normal((320, 3))
        ref = Estimator("scatter", backend="numpy", n_workers=4)
        got = Estimator("scatter", backend="cpp", n_workers=4)
        assert got.local_average(A, seed=0, scheme="swr") == pytest.approx(
            ref.local_average(A, seed=0, scheme="swr"), rel=1e-12)

    def test_triplet_falls_back_to_numpy(self):
        rng = np.random.default_rng(9)
        X = rng.standard_normal((40, 3))
        Y = rng.standard_normal((30, 3))
        ref = Estimator("triplet_indicator", backend="numpy").complete(X, Y)
        got = Estimator("triplet_indicator", backend="cpp").complete(X, Y)
        assert got == pytest.approx(ref, rel=1e-12)

    def test_custom_kernel_falls_back(self):
        from tuplewise_tpu.ops.kernels import Kernel

        k = Kernel(name="abs_diff", degree=2, two_sample=True,
                   kind="diff", diff_fn=lambda d, xp: xp.abs(d))
        rng = np.random.default_rng(10)
        a, b = rng.standard_normal(200), rng.standard_normal(150)
        ref = Estimator(k, backend="numpy").complete(a, b)
        got = Estimator(k, backend="cpp").complete(a, b)
        assert got == pytest.approx(ref, rel=1e-12)


@pytest.mark.perf
def test_faster_than_numpy(scores):
    """The native engine should beat the oracle it accelerates. A loaded
    CI box (OpenMP threads contending) can still lose the race without a
    correctness regression, so only require cpp <= 1.5x numpy and mark
    the test `perf` (deselect with `-m "not perf"`)."""
    import time

    X, Y = make_gaussians(4096, 4096, dim=1, separation=1.0, seed=0)
    s1, s2 = X[:, 0], Y[:, 0]
    en = Estimator("auc", backend="numpy")
    ec = Estimator("auc", backend="cpp")
    en.complete(s1, s2), ec.complete(s1, s2)  # warm

    def best_of(f, reps=3):
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            f()
            ts.append(time.perf_counter() - t0)
        return min(ts)

    # min-of-3 on both sides: robust to scheduler hiccups on loaded boxes
    assert best_of(lambda: ec.complete(s1, s2)) <= 1.5 * best_of(
        lambda: en.complete(s1, s2))


class TestTripletParity:
    """Degree-3 native path [r3]: the C++ triple loop mirrors
    NumpyBackend._triplet_stats (same i!=j id exclusion, same squared
    distances), so every scheme matches the oracle near-exactly."""

    @pytest.fixture(scope="class")
    def feats(self):
        rng = np.random.default_rng(11)
        return rng.standard_normal((40, 4)), rng.standard_normal((36, 4))

    @pytest.mark.parametrize(
        "kern", ["triplet_indicator", "triplet_hinge"]
    )
    def test_complete(self, feats, kern):
        X, Y = feats
        ref = Estimator(kern, backend="numpy").complete(X, Y)
        got = Estimator(kern, backend="cpp").complete(X, Y)
        assert got == pytest.approx(ref, rel=1e-12)

    def test_local_average_same_partitions(self, feats):
        X, Y = feats
        ref = Estimator("triplet_hinge", backend="numpy", n_workers=4)
        got = Estimator("triplet_hinge", backend="cpp", n_workers=4)
        for seed in range(3):
            assert got.local_average(X, Y, seed=seed) == pytest.approx(
                ref.local_average(X, Y, seed=seed), rel=1e-12)

    @pytest.mark.parametrize("design", ["swr", "swor", "bernoulli"])
    def test_incomplete_designs(self, feats, design):
        """Incomplete sampling inherits the shared host sampler, so
        tuple sets are identical at a seed (the kernel evaluation is
        NumPy either way — only complete/local hit the native loop)."""
        X, Y = feats
        a = Estimator("triplet_indicator", backend="numpy").incomplete(
            X, Y, n_pairs=2000, seed=3, design=design)
        b = Estimator("triplet_indicator", backend="cpp").incomplete(
            X, Y, n_pairs=2000, seed=3, design=design)
        assert a == pytest.approx(b, rel=1e-12)
