"""Degree-3 metric-learning SGD (models.triplet_sgd) [VERDICT r3
next #9]: the triplet-hinge learner must lift held-out triplet
accuracy through an embedding bottleneck, run distributed, and keep
its chunked trajectory exactly reproducible."""

import numpy as np
import pytest

from tuplewise_tpu.data import make_gaussians
from tuplewise_tpu.models.triplet_sgd import (
    TripletTrainConfig, evaluate_triplet_accuracy, init_embed,
    train_triplet,
)


@pytest.fixture(scope="module")
def rotated_clouds():
    X, Y = make_gaussians(160, 320, dim=8, separation=1.2, seed=0)
    q, _ = np.linalg.qr(
        np.random.default_rng(123).standard_normal((8, 8))
    )
    X, Y = (X @ q).astype(np.float32), (Y @ q).astype(np.float32)
    return X[:120], Y[:240], X[120:], Y[240:]


class TestTripletSGD:
    def test_learns_through_bottleneck(self, rotated_clouds):
        Xc_tr, Xo_tr, Xc_te, Xo_te = rotated_clouds
        p0 = init_embed(8, 2, seed=1)
        a0 = evaluate_triplet_accuracy(p0, Xc_te, Xo_te)
        cfg = TripletTrainConfig(
            lr=0.1, steps=120, n_workers=4, repartition_every=10,
            triplets_per_worker=1024, seed=0, embed_dim=2,
        )
        p1, hist = train_triplet(p0, Xc_tr, Xo_tr, cfg)
        a1 = evaluate_triplet_accuracy(p1, Xc_te, Xo_te)
        assert a1 > a0 + 0.05, (a0, a1)
        assert hist["loss"][-1] < hist["loss"][0]

    def test_curve_chunking_matches_straight_run(self, rotated_clouds):
        """eval_every chunks the scan; keys fold from absolute steps,
        so the final params must equal the unchunked run's exactly."""
        Xc_tr, Xo_tr, Xc_te, Xo_te = rotated_clouds
        p0 = init_embed(8, 2, seed=2)
        cfg = TripletTrainConfig(
            lr=0.1, steps=40, n_workers=4, repartition_every=8,
            triplets_per_worker=256, seed=3, embed_dim=2,
        )
        p_straight, _ = train_triplet(p0, Xc_tr, Xo_tr, cfg)
        p_chunked, hist = train_triplet(
            p0, Xc_tr, Xo_tr, cfg, eval_every=10,
            eval_data=(Xc_te, Xo_te),
        )
        np.testing.assert_allclose(
            p_chunked["W"], p_straight["W"], atol=1e-6
        )
        assert len(hist["test_acc"]) == 4

    def test_checkpoint_resume_exact(self, rotated_clouds, tmp_path):
        """Resume reproduces the straight run bit-for-bit (keys fold
        from absolute steps), and config mismatches are refused —
        the train_pairwise contract at degree 3 [SURVEY §5.5]."""
        Xc_tr, Xo_tr, _, _ = rotated_clouds
        p0 = init_embed(8, 2, seed=4)
        cfg = TripletTrainConfig(
            lr=0.1, steps=30, n_workers=4, repartition_every=8,
            triplets_per_worker=256, seed=5, embed_dim=2,
        )
        p_straight, h_straight = train_triplet(p0, Xc_tr, Xo_tr, cfg)
        ckpt = str(tmp_path / "triplet.npz")
        # phase 1: first 10 steps, checkpointed
        cfg10 = type(cfg)(**{**cfg.__dict__, "steps": 10})
        train_triplet(p0, Xc_tr, Xo_tr, cfg10, checkpoint_path=ckpt)
        # phase 2: resume to 30
        p_resumed, h_resumed = train_triplet(
            p0, Xc_tr, Xo_tr, cfg, checkpoint_path=ckpt
        )
        np.testing.assert_allclose(
            p_resumed["W"], p_straight["W"], atol=1e-7
        )
        np.testing.assert_allclose(
            h_resumed["loss"], h_straight["loss"], atol=1e-7
        )
        # config mismatch refuses to resume
        bad = type(cfg)(**{**cfg.__dict__, "lr": 0.2})
        with pytest.raises(ValueError):
            train_triplet(p0, Xc_tr, Xo_tr, bad, checkpoint_path=ckpt)

    def test_resume_preserves_eval_curve(self, rotated_clouds,
                                         tmp_path):
        """A resumed eval_every run carries the PRE-resume curve points
        and evaluates at the same absolute steps as the straight run
        (boundary realignment) — no silent truncation."""
        Xc_tr, Xo_tr, Xc_te, Xo_te = rotated_clouds
        p0 = init_embed(8, 2, seed=6)
        cfg = TripletTrainConfig(
            lr=0.1, steps=30, n_workers=4, repartition_every=8,
            triplets_per_worker=256, seed=8, embed_dim=2,
        )
        kw = dict(eval_every=10, eval_data=(Xc_te, Xo_te))
        _, h_straight = train_triplet(p0, Xc_tr, Xo_tr, cfg, **kw)
        ckpt = str(tmp_path / "curve.npz")
        cfg10 = type(cfg)(**{**cfg.__dict__, "steps": 10})
        train_triplet(p0, Xc_tr, Xo_tr, cfg10, checkpoint_path=ckpt,
                      **kw)
        _, h_resumed = train_triplet(p0, Xc_tr, Xo_tr, cfg,
                                     checkpoint_path=ckpt, **kw)
        np.testing.assert_array_equal(
            h_resumed["eval_steps"], h_straight["eval_steps"]
        )
        np.testing.assert_allclose(
            h_resumed["test_acc"], h_straight["test_acc"], atol=1e-7
        )

    def test_rejects_indicator_and_wrong_kind(self):
        with pytest.raises(ValueError, match="zero gradient"):
            train_triplet(
                init_embed(4, 2), np.zeros((8, 4)), np.zeros((8, 4)),
                TripletTrainConfig(kernel="triplet_indicator"),
            )
        with pytest.raises(ValueError, match="degree-3"):
            train_triplet(
                init_embed(4, 2), np.zeros((8, 4)), np.zeros((8, 4)),
                TripletTrainConfig(kernel="hinge"),
            )


class TestEmbedderPlugin:
    """Scorer-discipline embedders [VERDICT r4 next #9]: any frozen
    dataclass with apply(params, X, xp) trains through the same
    budgeted path; a bare {"W"} dict still means the linear map."""

    @staticmethod
    def _radial(seed, n=400):
        rng = np.random.default_rng(seed)

        def shell(m, r_lo, r_hi):
            v = rng.standard_normal((m, 8))
            v /= np.linalg.norm(v, axis=1, keepdims=True)
            r = rng.uniform(r_lo, r_hi, size=(m, 1))
            return (v * r).astype(np.float32)

        X, Y = shell(n, 0.5, 1.0), shell(2 * n, 1.8, 2.6)
        return X[:300], Y[:600], X[300:], Y[600:]

    def test_mlp_embedder_beats_linear_on_radial(self):
        """Radial classes (Bayes ceiling 1.0) are linearly
        inseparable: the linear embedding plateaus, the MLP through
        the SAME budgeted path climbs past it."""
        from tuplewise_tpu.models.scorers import LinearEmbed, MLPEmbed

        Xc_tr, Xo_tr, Xc_te, Xo_te = self._radial(0)
        cfg = TripletTrainConfig(
            lr=0.3, steps=400, n_workers=4, repartition_every=10,
            triplets_per_worker=1024, seed=0, embed_dim=2,
        )
        finals = {}
        for name, emb in (("linear", LinearEmbed(dim=8, embed_dim=2)),
                          ("mlp", MLPEmbed(dim=8, hidden=32,
                                           embed_dim=2))):
            p1, _ = train_triplet(emb.init(0), Xc_tr, Xo_tr, cfg,
                                  embedder=emb)
            finals[name] = evaluate_triplet_accuracy(
                p1, Xc_te, Xo_te, embedder=emb)
        assert finals["mlp"] > finals["linear"] + 0.05, finals

    def test_mlp_checkpoint_resume_and_mismatch(self, tmp_path):
        """MLP runs checkpoint/resume exactly; resuming with a
        different embedder fails as a config mismatch."""
        from tuplewise_tpu.models.scorers import MLPEmbed

        Xc_tr, Xo_tr, _, _ = self._radial(1)
        emb = MLPEmbed(dim=8, hidden=16, embed_dim=2)
        cfg = TripletTrainConfig(
            lr=0.1, steps=12, n_workers=4, repartition_every=4,
            triplets_per_worker=128, seed=2, embed_dim=2,
        )
        p_straight, h_straight = train_triplet(
            emb.init(1), Xc_tr, Xo_tr, cfg, embedder=emb)
        ckpt = str(tmp_path / "mlp.npz")
        cfg6 = type(cfg)(**{**cfg.__dict__, "steps": 6})
        train_triplet(emb.init(1), Xc_tr, Xo_tr, cfg6, embedder=emb,
                      checkpoint_path=ckpt)
        p_res, h_res = train_triplet(
            emb.init(1), Xc_tr, Xo_tr, cfg, embedder=emb,
            checkpoint_path=ckpt)
        for k in p_straight:
            np.testing.assert_array_equal(p_straight[k], p_res[k])
        np.testing.assert_allclose(h_straight["loss"], h_res["loss"],
                                   atol=1e-7)
        other = MLPEmbed(dim=8, hidden=32, embed_dim=2)
        with pytest.raises(ValueError):
            train_triplet(other.init(1), Xc_tr, Xo_tr, cfg,
                          embedder=other, checkpoint_path=ckpt)

    def test_bare_params_require_linear_shape(self):
        from tuplewise_tpu.models.scorers import MLPEmbed

        p_mlp = MLPEmbed(dim=8, hidden=16, embed_dim=2).init(0)
        with pytest.raises(ValueError, match="embedder"):
            train_triplet(
                p_mlp, np.zeros((16, 8), np.float32),
                np.zeros((16, 8), np.float32), TripletTrainConfig(),
            )
