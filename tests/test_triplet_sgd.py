"""Degree-3 metric-learning SGD (models.triplet_sgd) [VERDICT r3
next #9]: the triplet-hinge learner must lift held-out triplet
accuracy through an embedding bottleneck, run distributed, and keep
its chunked trajectory exactly reproducible."""

import numpy as np
import pytest

from tuplewise_tpu.data import make_gaussians
from tuplewise_tpu.models.triplet_sgd import (
    TripletTrainConfig, evaluate_triplet_accuracy, init_embed,
    train_triplet,
)


@pytest.fixture(scope="module")
def rotated_clouds():
    X, Y = make_gaussians(160, 320, dim=8, separation=1.2, seed=0)
    q, _ = np.linalg.qr(
        np.random.default_rng(123).standard_normal((8, 8))
    )
    X, Y = (X @ q).astype(np.float32), (Y @ q).astype(np.float32)
    return X[:120], Y[:240], X[120:], Y[240:]


class TestTripletSGD:
    def test_learns_through_bottleneck(self, rotated_clouds):
        Xc_tr, Xo_tr, Xc_te, Xo_te = rotated_clouds
        p0 = init_embed(8, 2, seed=1)
        a0 = evaluate_triplet_accuracy(p0, Xc_te, Xo_te)
        cfg = TripletTrainConfig(
            lr=0.1, steps=120, n_workers=4, repartition_every=10,
            triplets_per_worker=1024, seed=0, embed_dim=2,
        )
        p1, hist = train_triplet(p0, Xc_tr, Xo_tr, cfg)
        a1 = evaluate_triplet_accuracy(p1, Xc_te, Xo_te)
        assert a1 > a0 + 0.05, (a0, a1)
        assert hist["loss"][-1] < hist["loss"][0]

    def test_curve_chunking_matches_straight_run(self, rotated_clouds):
        """eval_every chunks the scan; keys fold from absolute steps,
        so the final params must equal the unchunked run's exactly."""
        Xc_tr, Xo_tr, Xc_te, Xo_te = rotated_clouds
        p0 = init_embed(8, 2, seed=2)
        cfg = TripletTrainConfig(
            lr=0.1, steps=40, n_workers=4, repartition_every=8,
            triplets_per_worker=256, seed=3, embed_dim=2,
        )
        p_straight, _ = train_triplet(p0, Xc_tr, Xo_tr, cfg)
        p_chunked, hist = train_triplet(
            p0, Xc_tr, Xo_tr, cfg, eval_every=10,
            eval_data=(Xc_te, Xo_te),
        )
        np.testing.assert_allclose(
            p_chunked["W"], p_straight["W"], atol=1e-6
        )
        assert len(hist["test_acc"]) == 4

    def test_rejects_indicator_and_wrong_kind(self):
        with pytest.raises(ValueError, match="zero gradient"):
            train_triplet(
                init_embed(4, 2), np.zeros((8, 4)), np.zeros((8, 4)),
                TripletTrainConfig(kernel="triplet_indicator"),
            )
        with pytest.raises(ValueError, match="degree-3"):
            train_triplet(
                init_embed(4, 2), np.zeros((8, 4)), np.zeros((8, 4)),
                TripletTrainConfig(kernel="hinge"),
            )
