"""Degree-3 metric-learning SGD (models.triplet_sgd) [VERDICT r3
next #9]: the triplet-hinge learner must lift held-out triplet
accuracy through an embedding bottleneck, run distributed, and keep
its chunked trajectory exactly reproducible."""

import numpy as np
import pytest

from tuplewise_tpu.data import make_gaussians
from tuplewise_tpu.models.triplet_sgd import (
    TripletTrainConfig, evaluate_triplet_accuracy, init_embed,
    train_triplet,
)


@pytest.fixture(scope="module")
def rotated_clouds():
    X, Y = make_gaussians(160, 320, dim=8, separation=1.2, seed=0)
    q, _ = np.linalg.qr(
        np.random.default_rng(123).standard_normal((8, 8))
    )
    X, Y = (X @ q).astype(np.float32), (Y @ q).astype(np.float32)
    return X[:120], Y[:240], X[120:], Y[240:]


class TestTripletSGD:
    def test_learns_through_bottleneck(self, rotated_clouds):
        Xc_tr, Xo_tr, Xc_te, Xo_te = rotated_clouds
        p0 = init_embed(8, 2, seed=1)
        a0 = evaluate_triplet_accuracy(p0, Xc_te, Xo_te)
        cfg = TripletTrainConfig(
            lr=0.1, steps=120, n_workers=4, repartition_every=10,
            triplets_per_worker=1024, seed=0, embed_dim=2,
        )
        p1, hist = train_triplet(p0, Xc_tr, Xo_tr, cfg)
        a1 = evaluate_triplet_accuracy(p1, Xc_te, Xo_te)
        assert a1 > a0 + 0.05, (a0, a1)
        assert hist["loss"][-1] < hist["loss"][0]

    def test_curve_chunking_matches_straight_run(self, rotated_clouds):
        """eval_every chunks the scan; keys fold from absolute steps,
        so the final params must equal the unchunked run's exactly."""
        Xc_tr, Xo_tr, Xc_te, Xo_te = rotated_clouds
        p0 = init_embed(8, 2, seed=2)
        cfg = TripletTrainConfig(
            lr=0.1, steps=40, n_workers=4, repartition_every=8,
            triplets_per_worker=256, seed=3, embed_dim=2,
        )
        p_straight, _ = train_triplet(p0, Xc_tr, Xo_tr, cfg)
        p_chunked, hist = train_triplet(
            p0, Xc_tr, Xo_tr, cfg, eval_every=10,
            eval_data=(Xc_te, Xo_te),
        )
        np.testing.assert_allclose(
            p_chunked["W"], p_straight["W"], atol=1e-6
        )
        assert len(hist["test_acc"]) == 4

    def test_checkpoint_resume_exact(self, rotated_clouds, tmp_path):
        """Resume reproduces the straight run bit-for-bit (keys fold
        from absolute steps), and config mismatches are refused —
        the train_pairwise contract at degree 3 [SURVEY §5.5]."""
        Xc_tr, Xo_tr, _, _ = rotated_clouds
        p0 = init_embed(8, 2, seed=4)
        cfg = TripletTrainConfig(
            lr=0.1, steps=30, n_workers=4, repartition_every=8,
            triplets_per_worker=256, seed=5, embed_dim=2,
        )
        p_straight, h_straight = train_triplet(p0, Xc_tr, Xo_tr, cfg)
        ckpt = str(tmp_path / "triplet.npz")
        # phase 1: first 10 steps, checkpointed
        cfg10 = type(cfg)(**{**cfg.__dict__, "steps": 10})
        train_triplet(p0, Xc_tr, Xo_tr, cfg10, checkpoint_path=ckpt)
        # phase 2: resume to 30
        p_resumed, h_resumed = train_triplet(
            p0, Xc_tr, Xo_tr, cfg, checkpoint_path=ckpt
        )
        np.testing.assert_allclose(
            p_resumed["W"], p_straight["W"], atol=1e-7
        )
        np.testing.assert_allclose(
            h_resumed["loss"], h_straight["loss"], atol=1e-7
        )
        # config mismatch refuses to resume
        bad = type(cfg)(**{**cfg.__dict__, "lr": 0.2})
        with pytest.raises(ValueError):
            train_triplet(p0, Xc_tr, Xo_tr, bad, checkpoint_path=ckpt)

    def test_resume_preserves_eval_curve(self, rotated_clouds,
                                         tmp_path):
        """A resumed eval_every run carries the PRE-resume curve points
        and evaluates at the same absolute steps as the straight run
        (boundary realignment) — no silent truncation."""
        Xc_tr, Xo_tr, Xc_te, Xo_te = rotated_clouds
        p0 = init_embed(8, 2, seed=6)
        cfg = TripletTrainConfig(
            lr=0.1, steps=30, n_workers=4, repartition_every=8,
            triplets_per_worker=256, seed=8, embed_dim=2,
        )
        kw = dict(eval_every=10, eval_data=(Xc_te, Xo_te))
        _, h_straight = train_triplet(p0, Xc_tr, Xo_tr, cfg, **kw)
        ckpt = str(tmp_path / "curve.npz")
        cfg10 = type(cfg)(**{**cfg.__dict__, "steps": 10})
        train_triplet(p0, Xc_tr, Xo_tr, cfg10, checkpoint_path=ckpt,
                      **kw)
        _, h_resumed = train_triplet(p0, Xc_tr, Xo_tr, cfg,
                                     checkpoint_path=ckpt, **kw)
        np.testing.assert_array_equal(
            h_resumed["eval_steps"], h_straight["eval_steps"]
        )
        np.testing.assert_allclose(
            h_resumed["test_acc"], h_straight["test_acc"], atol=1e-7
        )

    def test_rejects_indicator_and_wrong_kind(self):
        with pytest.raises(ValueError, match="zero gradient"):
            train_triplet(
                init_embed(4, 2), np.zeros((8, 4)), np.zeros((8, 4)),
                TripletTrainConfig(kernel="triplet_indicator"),
            )
        with pytest.raises(ValueError, match="degree-3"):
            train_triplet(
                init_embed(4, 2), np.zeros((8, 4)), np.zeros((8, 4)),
                TripletTrainConfig(kernel="hinge"),
            )
