"""Simulated-N trainer [VERDICT r2 next #1]: equivalence to the mesh
trainer is the load-bearing property — the learning-trade-off suite's
N=100+ runs are trustworthy exactly because N=8 reproduces the
distributed trajectory."""

import numpy as np
import pytest

from tuplewise_tpu.data import make_gaussian_splits
from tuplewise_tpu.models.pairwise_sgd import TrainConfig, train_pairwise
from tuplewise_tpu.models.scorers import LinearScorer, MLPScorer
from tuplewise_tpu.models.sim_learner import train_curves


@pytest.fixture(scope="module")
def data():
    return make_gaussian_splits(512, 1024, dim=5, separation=1.0, seed=0)


@pytest.fixture(scope="module")
def scorer():
    return LinearScorer(dim=5)


class TestMeshParity:
    @pytest.mark.parametrize("kernel,ppw,design", [
        ("hinge", None, "swr"), ("logistic", None, "swr"),
        ("hinge", 16, "swr"),
        # the on-device distinct designs [VERDICT r3 next #6] share the
        # exact fold chain and sampler between both trainers too
        ("hinge", 16, "swor"), ("logistic", 16, "bernoulli"),
    ])
    def test_matches_mesh_trainer(self, data, scorer, kernel, ppw,
                                  design):
        """Same TrainConfig + seed -> same trajectory as the shard_map
        trainer on the 8-device mesh (full-pair losses agree to float
        tolerance; sampled-pair paths share the exact fold chain and
        sampler, so indices are identical)."""
        Xp, Xn, _, _ = data
        p0 = scorer.init(0)
        cfg = TrainConfig(kernel=kernel, lr=0.3, steps=10, n_workers=8,
                          repartition_every=4, pairs_per_worker=ppw,
                          pair_design=design, seed=3)
        mesh_params, mesh_hist = train_pairwise(scorer, p0, Xp, Xn, cfg)
        out = train_curves(
            scorer, p0, Xp, Xn, Xp[:64], Xn[:64], cfg,
            n_seeds=1, eval_every=100,
        )
        sim_w = np.asarray(out["final_params"]["w"])[0]
        # f32 trajectories: the mesh's streamed-tile gradient and the
        # sim's dense grid differ only in summation order (~1e-6/step)
        np.testing.assert_allclose(sim_w, mesh_params["w"],
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(out["loss"][0], mesh_hist["loss"],
                                   rtol=2e-4, atol=2e-5)


class TestMLPParity:
    def test_mlp_matches_mesh_trainer(self, data):
        """Scorer-genericity: the nonlinear MLP pytree takes the same
        trajectory through both trainers."""
        Xp, Xn, _, _ = data
        scorer = MLPScorer(dim=5, hidden=8)
        p0 = scorer.init(2)
        cfg = TrainConfig(kernel="logistic", lr=0.3, steps=8,
                          n_workers=8, repartition_every=4, seed=5)
        mesh_params, _ = train_pairwise(scorer, dict(p0), Xp, Xn, cfg)
        out = train_curves(scorer, p0, Xp, Xn, Xp[:64], Xn[:64], cfg,
                           n_seeds=1, eval_every=100)
        for k in p0:
            np.testing.assert_allclose(
                np.asarray(out["final_params"][k])[0], mesh_params[k],
                rtol=2e-4, atol=2e-5, err_msg=k,
            )


class TestCurves:
    def test_chunking_invariant(self, data, scorer):
        """eval_every chunk boundaries never change the trajectory —
        keys fold from absolute step indices."""
        Xp, Xn, Xp_te, Xn_te = data
        p0 = scorer.init(0)
        cfg = TrainConfig(kernel="hinge", lr=0.3, steps=11, n_workers=8,
                          repartition_every=3, seed=1)
        a = train_curves(scorer, p0, Xp, Xn, Xp_te, Xn_te, cfg,
                         n_seeds=2, eval_every=4)
        b = train_curves(scorer, p0, Xp, Xn, Xp_te, Xn_te, cfg,
                         n_seeds=2, eval_every=100)
        np.testing.assert_allclose(
            np.asarray(a["final_params"]["w"]),
            np.asarray(b["final_params"]["w"]), rtol=1e-6,
        )
        np.testing.assert_array_equal(a["loss"], b["loss"])

    def test_auc_rises_and_shapes(self, data, scorer):
        Xp, Xn, Xp_te, Xn_te = data
        p0 = scorer.init(0)
        cfg = TrainConfig(kernel="hinge", lr=0.3, steps=40, n_workers=32,
                          repartition_every=5, seed=0)
        out = train_curves(scorer, p0, Xp, Xn, Xp_te, Xn_te, cfg,
                           n_seeds=3, eval_every=20)
        assert out["test_auc"].shape == (3, 3)      # init + 2 evals
        assert out["loss"].shape == (3, 40)
        assert list(out["steps"]) == [0, 20, 40]
        assert np.all(out["test_auc"][:, -1] > out["test_auc"][:, 0])

    def test_seeds_vary_partitions_not_init(self, data, scorer):
        """Replicas share the init (step-0 AUC identical) and diverge
        only through partition/sampling randomness."""
        Xp, Xn, Xp_te, Xn_te = data
        p0 = scorer.init(0)
        cfg = TrainConfig(kernel="hinge", lr=0.5, steps=6, n_workers=64,
                          repartition_every=1, seed=0)
        out = train_curves(scorer, p0, Xp, Xn, Xp_te, Xn_te, cfg,
                           n_seeds=4, eval_every=6)
        assert len(set(out["test_auc"][:, 0])) == 1
        w = np.asarray(out["final_params"]["w"])
        assert not np.allclose(w[0], w[1])

    def test_too_many_workers_raises(self, data, scorer):
        Xp, Xn, Xp_te, Xn_te = data
        cfg = TrainConfig(n_workers=4096)
        with pytest.raises(ValueError, match="too small"):
            train_curves(scorer, scorer.init(0), Xp, Xn, Xp_te, Xn_te,
                         cfg, n_seeds=1)


def test_cli_learning_subcommand(capsys):
    """The L6 surface covers the learning trade-off: one sweep cell via
    the CLI, emitting the same row schema as scripts/learning_suite."""
    import json

    from tuplewise_tpu.harness.cli import main

    rc = main([
        "learning", "--n", "256", "--steps", "20", "--n-workers", "16",
        "--repartition-every", "5", "--n-seeds", "2",
        "--eval-every", "10",
    ])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["n_r"] == 5
    assert rec["comm_events"] == 1 + 19 // 5
    assert len(rec["eval_steps"]) == len(rec["auc_mean"]) == 3
    assert 0.0 <= rec["final_auc_mean"] <= 1.0


def test_cli_learning_loss_free_and_design_flags(capsys):
    """--loss-every / --pair-design reach the TrainConfig [VERDICT r4
    next #1/#6 surface]; the emitted row stays valid JSON (the last
    RECORDED loss, never a NaN literal)."""
    import json

    from tuplewise_tpu.harness.cli import main

    rc = main([
        "learning", "--n", "256", "--steps", "8", "--n-workers", "8",
        "--n-seeds", "2", "--eval-every", "8", "--n-test", "512",
        "--pairs-per-worker", "16", "--pair-design", "swor",
        "--loss-every", "0",
    ])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["config"]["pair_design"] == "swor"
    assert rec["config"]["loss_every"] >= 1 << 30
    # only step 0 recorded; the summary is that value, not NaN
    assert rec["loss_final_mean"] is not None
    assert 0.0 <= rec["final_auc_mean"] <= 1.0


def test_learning_figures_render(tmp_path):
    """All four learning-trade-off figure kinds render from suite-shaped
    rows (incl. null-SE rows and the B=None all-pairs star)."""
    from tuplewise_tpu.harness.figures import (
        plot_auc_vs_budget, plot_auc_vs_comm, plot_learning_curves,
        plot_sd_vs_comm,
    )

    def row(nr, N=32, B=None, sd=1e-3):
        re_ = nr if nr is not None else 1 << 30
        return {
            "n_r": nr, "n_workers": N, "pairs_per_worker": B,
            "m_per_worker": [4, 4],
            "comm_events": 1 + 99 // re_,
            "eval_steps": [0, 50, 100],
            "auc_mean": [0.5, 0.7, 0.71],
            "auc_se": [0.0, 1e-3, 1e-3],
            "final_auc_mean": 0.71, "final_auc_se": sd / 2,
            "final_auc_sd": sd,
        }

    null_se = row(5)   # an n_seeds=1 row: no spread estimate anywhere
    null_se["auc_se"] = [None, None, None]
    null_se["final_auc_se"] = None
    null_se["final_auc_sd"] = None
    rows = [row(1), row(25), row(None, sd=3e-3), null_se]
    budget = [row(1, B=4), row(None, B=4), row(1), row(None)]
    import os

    for p in (
        plot_learning_curves(rows, str(tmp_path / "c.png")),
        plot_auc_vs_comm(rows, str(tmp_path / "a.png")),
        plot_sd_vs_comm(rows, str(tmp_path / "s.png")),
        plot_auc_vs_budget(budget, str(tmp_path / "b.png")),
    ):
        assert os.path.getsize(p) > 1000


def test_committed_chip_rows_match_cpu_rows():
    """Regression gate for the platform-independence claim (RESULTS
    §6): the committed TPU-chip sweep rows must match the committed
    CPU rows to f32 rounding — threefry is backend-deterministic, so
    the same seeds draw the same partitions and any larger divergence
    means a semantics change slipped into one path."""
    import json
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    chip_path = os.path.join(repo, "results", "learning_gauss_chip.jsonl")
    cpu_path = os.path.join(repo, "results", "learning_gauss.jsonl")
    if not (os.path.exists(chip_path) and os.path.exists(cpu_path)):
        pytest.skip("committed learning artifacts absent")
    chip = [json.loads(line) for line in open(chip_path)]
    cpu = [json.loads(line) for line in open(cpu_path)]
    assert chip, "empty chip artifact"
    for c in chip:
        match = [r for r in cpu
                 if r["n_workers"] == c["n_workers"]
                 and r["n_r"] == c["n_r"]
                 and r["pairs_per_worker"] == c["pairs_per_worker"]
                 and r["steps"] == c["steps"] and r["seed0"] == c["seed0"]]
        assert match, f"no CPU row for chip cell {c['n_workers']}/{c['n_r']}"
        m = match[0]
        # the gate is vacuous unless the rows really came from two
        # different platforms (a chip-stage rerun on a TPU-less host
        # would stamp cpu and compare cpu-to-cpu)
        assert c["platform"] == "tpu", c["platform"]
        assert m["platform"] == "cpu", m["platform"]
        # identical eval grids, else zip compares different steps
        assert c["eval_steps"] == m["eval_steps"]
        assert abs(c["final_auc_mean"] - m["final_auc_mean"]) < 5e-5
        for a, b in zip(c["auc_mean"], m["auc_mean"]):
            assert abs(a - b) < 1e-4, (c["n_r"], a, b)
