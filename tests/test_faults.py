"""Fault tolerance: drop-and-renormalize + failure detection [SURVEY §5.4].

The key properties:
* dropping workers leaves local-average / repartitioned estimators
  UNBIASED (each surviving worker's local U is unbiased on its own);
* the dropped-worker value equals the hand-computed mean over the
  surviving workers' per-worker values (exact renormalization, not an
  approximation);
* the numpy oracle and jax backend agree exactly for the same partition
  draw is NOT promised (different RNGs) — parity here is structural:
  identical semantics checked independently per backend.
"""

import jax
import numpy as np
import pytest

from tuplewise_tpu import Estimator
from tuplewise_tpu.data import make_gaussians
from tuplewise_tpu.parallel.faults import (
    alive_mask,
    check_mesh_health,
    normalize_dropped,
    sample_failures,
    survivors,
)

needs_mesh = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 (virtual) devices"
)


@pytest.fixture(scope="module")
def scores():
    X, Y = make_gaussians(1600, 1600, dim=1, separation=1.0, seed=3)
    return X[:, 0], Y[:, 0]


class TestFaultHelpers:
    def test_normalize_and_mask(self):
        assert normalize_dropped([3, 1, 1], 4) == (1, 3)
        assert alive_mask(4, (1, 3)).tolist() == [1.0, 0.0, 1.0, 0.0]
        assert survivors(4, (1, 3)) == (0, 2)

    def test_cannot_drop_all(self):
        with pytest.raises(ValueError, match="cannot drop all"):
            normalize_dropped(range(4), 4)

    def test_out_of_range(self):
        with pytest.raises(ValueError, match="out of range"):
            normalize_dropped([4], 4)

    def test_sample_failures_leaves_survivor(self):
        for seed in range(20):
            dropped = sample_failures(seed, 4, 0.9)
            assert len(dropped) < 4

    def test_sample_failures_rate(self):
        counts = [len(sample_failures(s, 16, 0.25)) for s in range(200)]
        assert 2.0 < np.mean(counts) < 6.0  # E = 4


class TestDropRenormalizeOracle:
    def test_equals_survivor_mean(self, scores):
        """Dropping workers == averaging the survivors' per-worker
        values, computed here independently from the same partition."""
        s1, s2 = scores
        from tuplewise_tpu.backends.numpy_backend import NumpyBackend
        from tuplewise_tpu.ops.kernels import auc_kernel
        from tuplewise_tpu.parallel.partition import partition_two_sample

        be = NumpyBackend(auc_kernel)
        rng = np.random.default_rng(11)
        pi, ni = partition_two_sample(len(s1), len(s2), 4, rng, "swor")
        per_worker = []
        for w in range(4):
            s, c = be._pair_stats(s1[pi[w]], s2[ni[w]])
            per_worker.append(s / c)
        got = be.local_average(
            s1, s2, n_workers=4, seed=11, scheme="swor",
            dropped_workers=(1, 2),
        )
        assert abs(got - np.mean([per_worker[0], per_worker[3]])) < 1e-12

    def test_unbiased_under_failures(self, scores):
        s1, s2 = scores
        est = Estimator("auc", backend="numpy", n_workers=4)
        u_n = est.complete(s1, s2)
        vals = [
            est.local_average(s1, s2, seed=m, dropped_workers=(2,))
            for m in range(40)
        ]
        se = np.std(vals) / np.sqrt(len(vals)) + 1e-6
        assert abs(np.mean(vals) - u_n) < 5 * se

    def test_repartitioned_with_failures(self, scores):
        s1, s2 = scores
        est = Estimator("auc", backend="numpy", n_workers=4)
        u_n = est.complete(s1, s2)
        vals = [
            est.repartitioned(
                s1, s2, n_rounds=3, seed=m, dropped_workers=(0,)
            )
            for m in range(25)
        ]
        se = np.std(vals) / np.sqrt(len(vals)) + 1e-6
        assert abs(np.mean(vals) - u_n) < 5 * se


class TestDropRenormalizeJax:
    def test_unbiased_under_failures(self, scores):
        s1, s2 = scores
        est = Estimator("auc", backend="jax", n_workers=4,
                        tile_a=128, tile_b=128)
        u_n = Estimator("auc", backend="numpy").complete(s1, s2)
        vals = [
            est.local_average(s1, s2, seed=m, dropped_workers=(1, 3))
            for m in range(40)
        ]
        se = np.std(vals) / np.sqrt(len(vals)) + 1e-6
        assert abs(np.mean(vals) - u_n) < 5 * se

    def test_dropped_changes_value_but_not_shape(self, scores):
        """Same seed, different failure sets -> different values (the
        mask is live, not ignored), with no recompilation error."""
        s1, s2 = scores
        est = Estimator("auc", backend="jax", n_workers=4,
                        tile_a=128, tile_b=128)
        full = est.local_average(s1, s2, seed=0)
        drop = est.local_average(s1, s2, seed=0, dropped_workers=(2,))
        assert full != drop


@needs_mesh
class TestDropRenormalizeMesh:
    def test_unbiased_under_failures(self, scores):
        s1, s2 = scores
        est = Estimator("auc", backend="mesh", n_workers=8,
                        tile_a=64, tile_b=64)
        u_n = Estimator("auc", backend="numpy").complete(s1, s2)
        vals = [
            est.local_average(s1, s2, seed=m, dropped_workers=(0, 5))
            for m in range(30)
        ]
        se = np.std(vals) / np.sqrt(len(vals)) + 1e-6
        assert abs(np.mean(vals) - u_n) < 5 * se

    def test_repartitioned_with_failures(self, scores):
        s1, s2 = scores
        est = Estimator("auc", backend="mesh", n_workers=8,
                        tile_a=64, tile_b=64)
        v = est.repartitioned(s1, s2, n_rounds=2, seed=0,
                              dropped_workers=(3,))
        assert 0.0 < v < 1.0

    def test_health_check(self):
        from tuplewise_tpu.parallel.mesh import make_mesh

        assert check_mesh_health(make_mesh(8))

    def test_health_check_2d(self):
        # regression: the probe must psum over ALL mesh axes — summing
        # only axis 0 of a (2, 4) mesh counts 2 devices, not 8, and
        # wrongly reports a healthy mesh as failed
        from tuplewise_tpu.parallel.mesh import make_mesh_2d

        assert check_mesh_health(make_mesh_2d(2, 4))


class TestEndToEndFaultTolerance:
    """run_with_fault_tolerance: probe -> dropped set -> estimator,
    with no manual glue [VERDICT r1 next #8]."""

    @needs_mesh
    def test_healthy_mesh_no_drops(self, scores):
        from tuplewise_tpu.parallel.faults import run_with_fault_tolerance

        s1, s2 = scores
        est = Estimator("auc", backend="mesh", n_workers=8,
                        tile_a=64, tile_b=64)
        v = run_with_fault_tolerance(est, "local", s1, s2, seed=0)
        assert v == est.local_average(s1, s2, seed=0)

    @needs_mesh
    def test_injected_failure_survives(self, scores, monkeypatch):
        """Simulate a dead chip: the collective probe reports unhealthy
        and the per-device probe fails for worker 3. One call must
        return the drop-and-renormalize value for dropped={3}."""
        import tuplewise_tpu.parallel.faults as faults

        s1, s2 = scores
        est = Estimator("auc", backend="mesh", n_workers=8,
                        tile_a=64, tile_b=64)
        dead = est.backend.mesh.devices.flat[3]

        monkeypatch.setattr(faults, "check_mesh_health", lambda mesh: False)
        real_put = jax.device_put

        def failing_put(x, dev=None, **kw):
            if dev is dead:
                raise RuntimeError("injected dead chip")
            return real_put(x, dev, **kw)

        monkeypatch.setattr(jax, "device_put", failing_put)
        v = faults.run_with_fault_tolerance(
            est, "repartitioned", s1, s2, n_rounds=2, seed=0
        )
        monkeypatch.undo()
        want = est.repartitioned(s1, s2, n_rounds=2, seed=0,
                                 dropped_workers=(3,))
        assert v == want

    @needs_mesh
    def test_detect_dropped_workers_healthy(self):
        from tuplewise_tpu.parallel.faults import detect_dropped_workers
        from tuplewise_tpu.parallel.mesh import make_mesh

        assert detect_dropped_workers(make_mesh(8)) == ()

    def test_rejects_complete_scheme(self, scores):
        from tuplewise_tpu.parallel.faults import run_with_fault_tolerance

        s1, s2 = scores
        est = Estimator("auc", backend="numpy", n_workers=4)
        with pytest.raises(ValueError, match="schemes"):
            run_with_fault_tolerance(est, "complete", s1, s2)

    def test_numpy_backend_detector_default(self, scores):
        """Single-process backends default to a no-failure detector."""
        from tuplewise_tpu.parallel.faults import run_with_fault_tolerance

        s1, s2 = scores
        est = Estimator("auc", backend="numpy", n_workers=4)
        v = run_with_fault_tolerance(est, "local", s1, s2, seed=1)
        assert v == est.local_average(s1, s2, seed=1)


class TestProbeTimeout:
    """[ISSUE 3 satellite] A HUNG device blocks forever instead of
    raising — the detector must bound the probe, or it becomes the very
    hang it exists to detect."""

    def test_hung_collective_reports_unhealthy(self, monkeypatch):
        import time

        import tuplewise_tpu.parallel.faults as faults
        from tuplewise_tpu.parallel.mesh import make_mesh

        monkeypatch.setattr(faults, "_collective_probe",
                            lambda mesh: time.sleep(60))
        t0 = time.monotonic()
        assert faults.check_mesh_health(make_mesh(1),
                                        timeout_s=0.2) is False
        assert time.monotonic() - t0 < 5.0

    def test_hung_device_counted_dropped(self, monkeypatch):
        import time

        import tuplewise_tpu.parallel.faults as faults
        from tuplewise_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(2)
        hung = mesh.devices.flat[1]
        monkeypatch.setattr(faults, "_collective_probe",
                            lambda mesh: False)

        def probe(dev):
            if dev is hung:
                time.sleep(60)
            return True

        monkeypatch.setattr(faults, "_device_probe", probe)
        t0 = time.monotonic()
        assert faults.detect_dropped_workers(mesh, timeout_s=0.2) == (1,)
        assert time.monotonic() - t0 < 5.0

    def test_no_timeout_keeps_sync_path(self):
        from tuplewise_tpu.parallel.faults import check_mesh_health
        from tuplewise_tpu.parallel.mesh import make_mesh

        assert check_mesh_health(make_mesh(1))   # timeout_s=None


class TestFaults2DMesh:
    @needs_mesh
    def test_drop_renormalize_on_2d_mesh(self):
        """Drop-and-renormalize works unchanged over the hierarchical
        (dcn x ici) mesh: the alive mask indexes the LINEARIZED worker
        id, so a 2-D local average with dropped workers must equal the
        1-D mesh's value at the same seed (identical fold chains)."""
        from tuplewise_tpu.parallel.mesh import make_mesh_2d

        X, Y = make_gaussians(512, 512, dim=1, separation=1.0, seed=3)
        s1, s2 = X[:, 0], Y[:, 0]
        flat = Estimator("auc", backend="mesh", n_workers=8,
                         tile_a=64, tile_b=64)
        hier = Estimator("auc", backend="mesh", mesh=make_mesh_2d(2, 4),
                         tile_a=64, tile_b=64)
        for dropped in ((), (3,), (0, 6)):
            a = flat.local_average(s1, s2, seed=5, dropped_workers=dropped)
            b = hier.local_average(s1, s2, seed=5, dropped_workers=dropped)
            assert abs(a - b) < 1e-6, dropped
        # dropping changes the value (the mask is live on 2-D too)
        a0 = hier.local_average(s1, s2, seed=5)
        a1 = hier.local_average(s1, s2, seed=5, dropped_workers=(2,))
        assert a0 != a1
