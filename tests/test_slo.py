"""obs.slo [ISSUE 7]: spec parsing, objective evaluation, multi-window
burn rates, breach transitions (flight events + gauges), reports."""

import json

import pytest

from tuplewise_tpu.obs.flight import FlightRecorder
from tuplewise_tpu.obs.slo import (
    DEFAULT_DOCTOR_SPEC, SloMonitor, SloSpec, SloSpecError,
    evaluate_history,
)
from tuplewise_tpu.utils.profiling import MetricsRegistry


def _m(counters=None, hists=None, gauges=None):
    """A snapshot-shaped metrics dict from plain numbers."""
    out = {}
    for k, v in (counters or {}).items():
        out[k] = {"type": "counter", "value": v}
    for k, v in (gauges or {}).items():
        out[k] = {"type": "gauge", "value": v}
    for k, q in (hists or {}).items():
        out[k] = dict({"type": "histogram", "count": 1}, **q)
    return out


LAT = {"objectives": [
    {"name": "p99", "type": "latency", "metric": "insert_latency_s",
     "quantile": "p99", "threshold_ms": 10.0}]}


class TestSpecParsing:
    def test_dict_json_and_file_forms(self, tmp_path):
        spec = SloSpec.from_spec(LAT)
        assert spec.objectives[0].name == "p99"
        spec = SloSpec.from_spec(json.dumps(LAT))
        assert spec.objectives[0].threshold_ms == 10.0
        p = tmp_path / "slo.json"
        p.write_text(json.dumps(LAT))
        assert SloSpec.from_spec(str(p)).objectives[0].name == "p99"
        assert SloSpec.from_spec(f"@{p}").objectives[0].name == "p99"

    def test_idempotent_on_parsed_spec(self):
        spec = SloSpec.from_spec(LAT)
        assert SloSpec.from_spec(spec) is spec

    @pytest.mark.parametrize("bad", [
        {"objectives": []},
        {"objectives": [{"name": "x", "type": "nope"}]},
        {"objectives": [{"type": "latency", "metric": "m",
                         "threshold_ms": 1}]},          # no name
        {"objectives": [{"name": "x", "type": "latency",
                         "metric": "m"}]},              # no threshold
        {"objectives": [{"name": "x", "type": "latency", "metric": "m",
                         "threshold_ms": 1, "quantile": "p42"}]},
        {"objectives": [{"name": "x", "type": "error_rate",
                         "errors": ["e"], "total": "t"}]},  # no objective
        {"objectives": [{"name": "x", "type": "error_rate",
                         "errors": ["e"], "total": "t",
                         "objective": 0.99,
                         "windows": [{"window_s": 0, "burn": 1}]}]},
        {"objectives": [{"name": "x", "type": "counter_max"}]},
        {"objectives": [{"name": "x", "type": "saturation",
                         "metric": "g"}]},              # no capacity
        {"objectives": [LAT["objectives"][0], LAT["objectives"][0]]},
    ])
    def test_invalid_specs_raise(self, bad):
        with pytest.raises(SloSpecError):
            SloSpec.from_spec(bad)

    def test_window_extents(self):
        spec = SloSpec.from_spec({"objectives": [
            {"name": "a", "type": "error_rate", "errors": ["e"],
             "total": "t", "objective": 0.9,
             "windows": [{"window_s": 2, "burn": 5},
                         {"window_s": 30, "burn": 1}]}]})
        assert spec.longest_window_s == 30
        assert spec.shortest_window_s == 2


class TestLatencyObjective:
    def test_breach_transition_and_recovery(self):
        reg = MetricsRegistry()
        fl = FlightRecorder()
        mon = SloMonitor(LAT, registry=reg, flight=fl)
        assert mon.observe(_m(hists={"insert_latency_s": {"p99": 0.005}}),
                           0.0) == []
        tr = mon.observe(_m(hists={"insert_latency_s": {"p99": 0.050}}),
                         1.0)
        assert len(tr) == 1 and tr[0]["objective"] == "p99"
        # staying breached is NOT a new transition
        assert mon.observe(
            _m(hists={"insert_latency_s": {"p99": 0.060}}), 2.0) == []
        assert mon.observe(
            _m(hists={"insert_latency_s": {"p99": 0.002}}), 3.0) == []
        # exactly one flight event, gauges track live state
        assert len(fl.events("slo_breach")) == 1
        snap = reg.snapshot()
        assert snap["slo_breached{objective=p99}"]["value"] == 0.0
        assert snap["slo_breaches_total{objective=p99}"]["value"] == 1
        rep = mon.report()
        assert rep["breached_ever"] and not rep["breached_now"]
        assert not rep["healthy"]
        assert rep["objectives"]["p99"]["breaches_total"] == 1

    def test_missing_metric_is_not_a_breach(self):
        mon = SloMonitor(LAT)
        assert mon.observe(_m(), 0.0) == []
        assert mon.report()["healthy"]


ERR = {"objectives": [
    {"name": "avail", "type": "error_rate",
     "errors": ["rejected_total", "dropped_total"],
     "total": "requests_insert_total", "objective": 0.9,
     "windows": [{"window_s": 10, "burn": 2.0},
                 {"window_s": 60, "burn": 1.0}]}]}


def _err_snap(total, errs):
    return _m(counters={"requests_insert_total": total,
                        "rejected_total": errs, "dropped_total": 0})


class TestErrorRateBurn:
    def test_all_windows_must_exceed(self):
        mon = SloMonitor(ERR)
        # budget = 0.1. A fast burn confined to the short window: long
        # window rate stays low -> no breach (multi-window AND)
        mon.observe(_err_snap(0, 0), 0.0)
        for i in range(1, 7):
            mon.observe(_err_snap(i * 1000, 0), i * 10.0)
        # short window: 50% errors (burn 5 > 2); long window includes
        # 6000 clean requests -> rate 500/7000 ~ 0.071, burn 0.71 < 1
        tr = mon.observe(_err_snap(7000, 500), 70.0)
        assert tr == []
        assert not mon.report()["breached_ever"]

    def test_sustained_burn_breaches(self):
        mon = SloMonitor(ERR)
        mon.observe(_err_snap(0, 0), 0.0)
        fired = []
        # 30% error rate sustained across both windows (burn 3 > both)
        for i in range(1, 9):
            fired += mon.observe(_err_snap(i * 1000, i * 300), i * 10.0)
        assert len(fired) == 1
        rep = mon.report()["objectives"]["avail"]
        assert rep["breaches_total"] == 1
        wins = rep["last"]["windows"]
        assert set(wins) == {"10s", "60s"}
        assert wins["60s"]["burn_rate"] == pytest.approx(3.0)

    def test_zero_traffic_is_healthy(self):
        mon = SloMonitor(ERR)
        for i in range(8):
            assert mon.observe(_err_snap(0, 0), i * 10.0) == []

    def test_short_history_uses_oldest_snapshot(self):
        # with only 2 snapshots, both windows difference against the
        # first — a conservative shorter window, never a crash
        mon = SloMonitor(ERR)
        mon.observe(_err_snap(0, 0), 0.0)
        tr = mon.observe(_err_snap(100, 50), 1.0)
        assert len(tr) == 1      # 50% errors, burn 5 in both windows


class TestCounterAndSaturation:
    def test_counter_max(self):
        spec = {"objectives": [{"name": "heal", "type": "counter_max",
                                "metric": "heal_exhausted_total"}]}
        mon = SloMonitor(spec)
        assert mon.observe(_m(counters={"heal_exhausted_total": 0}),
                           0.0) == []
        tr = mon.observe(_m(counters={"heal_exhausted_total": 1}), 1.0)
        assert len(tr) == 1
        # a cumulative counter cannot recover
        assert mon.report()["breached_now"]

    def test_saturation_with_symbolic_capacity(self):
        spec = {"objectives": [{"name": "q", "type": "saturation",
                                "metric": "queue_depth_live",
                                "capacity": "queue_size",
                                "max_fraction": 0.9}]}
        mon = SloMonitor(spec, context={"queue_size": 100})
        assert mon.observe(_m(gauges={"queue_depth_live": 80}),
                           0.0) == []
        assert len(mon.observe(_m(gauges={"queue_depth_live": 95}),
                               1.0)) == 1
        assert mon.observe(_m(gauges={"queue_depth_live": 10}),
                           2.0) == []
        assert not mon.report()["breached_now"]

    def test_unresolved_capacity_never_breaches(self):
        spec = {"objectives": [{"name": "q", "type": "saturation",
                                "metric": "queue_depth_live",
                                "capacity": "nope"}]}
        mon = SloMonitor(spec)
        assert mon.observe(_m(gauges={"queue_depth_live": 1e9}),
                           0.0) == []


class TestHistoryAndDefaults:
    def test_evaluate_history_rows(self):
        rows = [{"ts_mono": float(i),
                 "metrics": _err_snap(i * 100, i * 30)}
                for i in range(10)]
        rep = evaluate_history(ERR, rows)
        assert rep["evaluations"] == 10
        assert rep["breached_ever"]

    def test_default_doctor_spec_parses_and_passes_clean(self):
        rows = [{"ts_mono": float(i), "metrics": _m(
            counters={"requests_insert_total": i * 50,
                      "rejected_total": 0, "dropped_total": 0,
                      "deadline_expired_total": 0,
                      "heal_exhausted_total": 0})}
            for i in range(5)]
        rep = evaluate_history(DEFAULT_DOCTOR_SPEC, rows)
        assert rep["healthy"]


class TestLabelWildcards:
    """[ISSUE 8 satellite] ``metric{label=*}`` objectives fan out over
    every matching labeled series — one spec line covers a fleet."""

    def _tenant_registry(self):
        reg = MetricsRegistry()
        for t, lat in (("a", 0.001), ("b", 0.2), ("c", 0.003)):
            h = reg.histogram("insert_latency_s", labels={"tenant": t})
            for _ in range(8):
                h.observe(lat)
        return reg

    def test_latency_wildcard_breaches_on_any_series(self):
        reg = self._tenant_registry()
        mon = SloMonitor({"objectives": [
            {"name": "tp99", "type": "latency",
             "metric": "insert_latency_s{tenant=*}",
             "quantile": "p99", "threshold_ms": 50}]}, registry=reg)
        transitions = mon.observe(reg.snapshot(), 1.0)
        assert len(transitions) == 1
        rep = mon.report()
        series = rep["objectives"]["tp99"]["last"]["series"]
        assert series["tenant=b"]["breached"]
        assert not series["tenant=a"]["breached"]
        assert rep["objectives"]["tp99"]["last"]["series_breached"] == 1

    def test_per_series_breach_gauges_exported(self):
        reg = self._tenant_registry()
        mon = SloMonitor({"objectives": [
            {"name": "tp99", "type": "latency",
             "metric": "insert_latency_s{tenant=*}",
             "quantile": "p99", "threshold_ms": 50}]}, registry=reg)
        mon.observe(reg.snapshot(), 1.0)
        snap = reg.snapshot()
        assert snap["slo_breached{objective=tp99,tenant=b}"][
            "value"] == 1.0
        assert snap["slo_breached{objective=tp99,tenant=a}"][
            "value"] == 0.0
        assert snap["slo_breached{objective=tp99}"]["value"] == 1.0

    def test_counter_max_wildcard(self):
        m = _m(counters={"tenant_rejected_total{tenant=x}": 0,
                         "tenant_rejected_total{tenant=y}": 3})
        mon = SloMonitor({"objectives": [
            {"name": "rej", "type": "counter_max",
             "metric": "tenant_rejected_total{tenant=*}", "max": 0}]})
        mon.observe(m, 0.0)
        last = mon.report()["objectives"]["rej"]["last"]
        assert last["series"]["tenant=y"]["breached"]
        assert not last["series"]["tenant=x"]["breached"]

    def test_wildcard_no_matches_is_healthy(self):
        mon = SloMonitor({"objectives": [
            {"name": "tp99", "type": "latency",
             "metric": "insert_latency_s{tenant=*}",
             "quantile": "p99", "threshold_ms": 50}]})
        assert mon.observe(_m(), 0.0) == []
        assert mon.report()["healthy"]

    def test_error_rate_wildcard_sums_series(self):
        def snap(err_x, err_y, total):
            return _m(counters={
                "tenant_rejected_total{tenant=x}": err_x,
                "tenant_rejected_total{tenant=y}": err_y,
                "requests_insert_total": total})
        spec = {"objectives": [
            {"name": "avail", "type": "error_rate",
             "errors": ["tenant_rejected_total{tenant=*}"],
             "total": "requests_insert_total", "objective": 0.9,
             "windows": [{"window_s": 1.0, "burn": 1.0}]}]}
        mon = SloMonitor(spec)
        mon.observe(snap(0, 0, 100), 0.0)
        mon.observe(snap(30, 30, 200), 2.0)   # 60 errors / 100 events
        rep = mon.report()
        assert rep["objectives"]["avail"]["breaches_total"] == 1

    def test_match_series_exact_labels_respected(self):
        from tuplewise_tpu.obs.slo import match_series

        m = _m(counters={"c{region=eu,tenant=a}": 1,
                         "c{region=us,tenant=b}": 2, "c": 3})
        got = match_series(m, "c{region=eu,tenant=*}")
        assert len(got) == 1
        assert got[0][0] == {"tenant": "a"}
