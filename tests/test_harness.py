"""L4/L6 harness: vmapped Monte-Carlo correctness, trade-off shapes,
CLI, figures, triplet experiment."""

import dataclasses
import json
import subprocess
import sys

import numpy as np
import pytest

from tuplewise_tpu.data import make_gaussians, true_gaussian_auc
from tuplewise_tpu.harness import (
    VarianceConfig,
    run_variance_experiment,
    tradeoff_vs_pairs,
    tradeoff_vs_rounds,
    triplet_mnist_statistic,
)


BASE = VarianceConfig(n_pos=512, n_neg=512, n_workers=8, n_reps=64)


class TestVarianceExperiment:
    def test_complete_vmapped_matches_population(self):
        r = run_variance_experiment(BASE)
        assert r["vmapped"]
        assert abs(r["mean"] - true_gaussian_auc(1.0)) < 5 * r["std_error"] + 1e-3

    def test_complete_variance_matches_hoeffding(self):
        """Empirical MC variance ~ closed-form Hoeffding variance
        [SURVEY §5.1 'Statistical tests'] — the harness's own oracle."""
        cfg = VarianceConfig(n_pos=256, n_neg=256, n_reps=400)
        r = run_variance_experiment(cfg)
        # variance formula at n=256 via zetas from a large plug-in sample
        from tuplewise_tpu.estimators.variance import (
            two_sample_variance_from_zetas,
            two_sample_zetas,
        )
        X, Y = make_gaussians(20_000, 20_000, 1, 1.0, seed=123)
        z = two_sample_zetas("auc", X[:, 0], Y[:, 0])
        pred = two_sample_variance_from_zetas(z, 256, 256)
        assert abs(r["variance"] - pred) / pred < 0.35

    def test_schemes_ordering(self):
        """Var(complete) <= Var(repartitioned T=4) <= Var(local)
        [SURVEY §1.2] on conditional-free MC over fresh draws."""
        out = {}
        for scheme, kw in [
            ("complete", {}),
            ("repartitioned", {"n_rounds": 4}),
            ("local", {}),
        ]:
            cfg = VarianceConfig(
                n_pos=128, n_neg=128, n_workers=8, n_reps=300,
                scheme=scheme, **kw,
            )
            out[scheme] = run_variance_experiment(cfg)["variance"]
        assert out["complete"] <= out["repartitioned"] * 1.2
        assert out["repartitioned"] < out["local"] * 1.2

    def test_incomplete_variance_formula(self):
        cfg = VarianceConfig(
            n_pos=512, n_neg=512, scheme="incomplete", n_pairs=500,
            n_reps=400,
        )
        r = run_variance_experiment(cfg)
        X, Y = make_gaussians(40_000, 40_000, 1, 1.0, seed=77)
        # incomplete-variance formula at n=512 via large-sample zetas
        from tuplewise_tpu.estimators.variance import (
            two_sample_variance_from_zetas,
            two_sample_zetas,
        )
        z = two_sample_zetas("auc", X[:, 0], Y[:, 0])
        pred = two_sample_variance_from_zetas(z, 512, 512) + (
            z[2] - two_sample_variance_from_zetas(z, 512, 512)
        ) / 500
        assert abs(r["variance"] - pred) / pred < 0.35

    def test_dense_many_workers_local_matches_closed_form(self):
        """Small per-worker blocks take the dense [N, m1, m2] broadcast
        path; its variance must match the Hoeffding closed form and sit
        visibly ABOVE the complete-U floor (the paper's trade-off
        regime) [SURVEY §1.2 item 2, §5.1]."""
        cfg = VarianceConfig(
            n_pos=96, n_neg=96, n_workers=24, n_reps=400, scheme="local"
        )
        r = run_variance_experiment(cfg)
        assert r["vmapped"]
        assert abs(r["mean"] - true_gaussian_auc(1.0)) < 5 * r["std_error"]

        from tuplewise_tpu.estimators.variance import (
            two_sample_variance_from_zetas, two_sample_zetas,
        )

        X, Y = make_gaussians(20_000, 20_000, 1, 1.0, seed=5)
        z = two_sample_zetas("auc", X[:, 0], Y[:, 0])
        v_loc = two_sample_variance_from_zetas(z, 4, 4) / 24
        v_comp = two_sample_variance_from_zetas(z, 96, 96)
        # the deficit scales as (zeta_11/(zeta_10+zeta_01) - 1)/m,
        # about +25% at m=4 (zeta_11 ~ 2x zeta' for Gaussian AUC)
        assert v_loc > 1.08 * v_comp       # the gap exists in theory...
        assert 0.6 * v_loc < r["variance"] < 1.6 * v_loc   # ...and in MC

    def test_pallas_branch_interpret_parity(self, monkeypatch):
        """TUPLEWISE_HARNESS_PALLAS=interpret exercises the TPU-only
        Pallas routing of the vmapped runner on CPU: same estimates as
        the XLA scan path to float32 tolerance."""
        monkeypatch.setenv("TUPLEWISE_HARNESS_PALLAS", "off")
        # n/N large enough that local blocks (m1*m2 = 90000 > 2^16)
        # stay OFF the dense path — both schemes here must route
        # through hot_pair_mean or the parity is vacuous
        cfg = VarianceConfig(n_pos=600, n_neg=600, n_workers=2, n_reps=4)
        xla = run_variance_experiment(cfg)
        monkeypatch.setenv("TUPLEWISE_HARNESS_PALLAS", "interpret")
        pal = run_variance_experiment(cfg)
        assert pal["vmapped"] and xla["vmapped"]
        assert abs(pal["mean"] - xla["mean"]) < 1e-6
        loc = run_variance_experiment(
            dataclasses.replace(cfg, scheme="local")
        )
        monkeypatch.setenv("TUPLEWISE_HARNESS_PALLAS", "off")
        loc_xla = run_variance_experiment(
            dataclasses.replace(cfg, scheme="local")
        )
        assert abs(loc["mean"] - loc_xla["mean"]) < 1e-6

    def test_pallas_smem_guard_and_tile_picker(self):
        """The unmasked kernel refuses row-block counts past the SMEM
        budget (clear error, no Mosaic crash); the tile picker narrows
        lanes for transcendental kernels."""
        import jax.numpy as jnp

        from tuplewise_tpu.ops.kernels import (
            auc_kernel, logistic_kernel,
        )
        from tuplewise_tpu.ops.pallas_pairs import (
            pallas_pair_sum, preferred_pair_tiles,
        )

        big = jnp.zeros(256 * 1537, jnp.float32)
        with pytest.raises(ValueError, match="SMEM"):
            pallas_pair_sum(
                big, big[:4096], kernel=auc_kernel,
                tile_a=256, tile_b=4096, interpret=True,
            )
        assert preferred_pair_tiles(auc_kernel, 10**6, 10**6) == (2048, 8192)
        assert preferred_pair_tiles(logistic_kernel, 10**6, 10**6) == (2048, 2048)
        assert preferred_pair_tiles(auc_kernel, 300, 300) == (256, 2048)

    def test_numpy_backend_loop_path(self):
        cfg = VarianceConfig(
            backend="numpy", n_pos=128, n_neg=128, n_reps=20,
        )
        r = run_variance_experiment(cfg)
        assert not r["vmapped"]
        assert abs(r["mean"] - true_gaussian_auc(1.0)) < 0.05


class TestDesignedIncompleteHarness:
    """swor/bernoulli designs MEASURED through the MC harness
    [VERDICT r3 next #4]. Unconditionally the design difference is
    sigma_h^2/G — invisible against Var(U_n); the measurement that
    resolves it is CONDITIONAL on a frozen dataset (fix_data=True),
    where the closed forms are exact: s^2 = U(1-U) for the indicator
    kernel, and swor at B = G/2 halves the swr variance."""

    @staticmethod
    def _conditional(design, n_reps=1_500):
        cfg = VarianceConfig(
            n_pos=100, n_neg=100, separation=0.25, scheme="incomplete",
            n_pairs=5_000, design=design, n_reps=n_reps, n_workers=2,
            fix_data=True,
        )
        return cfg, run_variance_experiment(cfg)

    @staticmethod
    def _exact_targets(cfg):
        from tuplewise_tpu.estimators.variance import (
            conditional_incomplete_variance,
        )
        from tuplewise_tpu.harness.variance import fixed_dataset
        from tuplewise_tpu.models.metrics import auc_score

        s1, s2 = fixed_dataset(cfg)
        u = auc_score(s1, s2)
        pred = conditional_incomplete_variance(
            u * (1 - u), cfg.n_pos * cfg.n_neg,
            n_pairs=cfg.n_pairs, design=cfg.design,
        )
        return u, pred

    def test_swor_halves_conditional_variance_vs_swr(self):
        # B = G/2 here: fpc = 1/2 exactly (up to G/(G-1))
        cfg_r, r_swr = self._conditional("swr")
        cfg_o, r_swor = self._conditional("swor")
        assert r_swr["vmapped"] and r_swor["vmapped"]
        u, pred_swr = self._exact_targets(cfg_r)
        _, pred_swor = self._exact_targets(cfg_o)
        assert pred_swor == pytest.approx(pred_swr / 2, rel=1e-3)
        # SE(var)/var ~ sqrt(2/M) = 3.7% at M=1500; 4-sigma bounds
        assert abs(r_swr["variance"] - pred_swr) / pred_swr < 0.15
        assert abs(r_swor["variance"] - pred_swor) / pred_swor < 0.15
        # the factor-2 reduction as a direct measurement
        ratio = r_swor["variance"] / r_swr["variance"]
        assert 0.35 < ratio < 0.65, ratio
        # conditional means are unbiased for the FIXED-data complete U
        for r in (r_swr, r_swor):
            assert abs(r["mean"] - u) < 5 * r["std_error"]

    def test_bernoulli_conditional_matches_swor_form(self):
        cfg, r = self._conditional("bernoulli", n_reps=1_000)
        assert r["vmapped"]
        u, pred = self._exact_targets(cfg)
        assert abs(r["variance"] - pred) / pred < 0.2
        assert abs(r["mean"] - u) < 5 * r["std_error"]

    def test_designed_closed_form_hits_complete_floor_at_full_grid(self):
        from tuplewise_tpu.estimators.variance import (
            incomplete_variance_from_zetas,
            two_sample_variance_from_zetas,
            two_sample_zetas,
        )

        X, Y = make_gaussians(40_000, 40_000, 1, 1.0, seed=77)
        z = two_sample_zetas("auc", X[:, 0], Y[:, 0])
        full = incomplete_variance_from_zetas(
            z, 64, 64, n_pairs=64 * 64, design="swor"
        )
        assert full == pytest.approx(
            two_sample_variance_from_zetas(z, 64, 64), rel=1e-12
        )


class TestTradeoffs:
    def test_variance_decreases_with_rounds(self):
        cfg = VarianceConfig(n_pos=128, n_neg=128, n_workers=8, n_reps=200)
        rs = tradeoff_vs_rounds(cfg, rounds=(1, 8))
        assert rs[1]["variance"] < rs[0]["variance"]

    def test_variance_decreases_with_pairs(self):
        cfg = VarianceConfig(n_pos=512, n_neg=512, n_reps=150)
        rs = tradeoff_vs_pairs(cfg, pairs=(100, 10_000))
        assert rs[1]["variance"] < rs[0]["variance"]


class TestTriplet:
    def test_mnist_triplet_statistic(self):
        r = triplet_mnist_statistic(n=400, n_pairs=2000, backend="jax")
        assert 0.9 < r["mean"] <= 1.0  # well-separated surrogate classes
        assert len(r["per_class"]) == 10

    def test_complete_small(self):
        r = triplet_mnist_statistic(n=150, n_pairs=None, backend="numpy")
        assert 0.9 < r["mean"] <= 1.0


class TestCLIAndFigures:
    def test_cli_variance_json(self, tmp_path):
        out = tmp_path / "r.jsonl"
        proc = subprocess.run(
            [sys.executable, "-m", "tuplewise_tpu.harness.cli", "variance",
             "--n-pos", "128", "--n-neg", "128", "--n-reps", "10",
             "--backend", "numpy", "--out", str(out)],
            capture_output=True, text=True,
            env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin",
                 "PYTHONPATH": "/root/repo"},
        )
        assert proc.returncode == 0, proc.stderr
        r = json.loads(proc.stdout.strip().splitlines()[-1])
        assert 0.5 < r["mean"] < 1.0
        assert out.exists()

    def test_figures(self, tmp_path):
        from tuplewise_tpu.harness.figures import (
            plot_variance_vs_pairs,
            plot_variance_vs_rounds,
            plot_variance_vs_wallclock,
        )

        cfg = VarianceConfig(n_pos=128, n_neg=128, n_reps=30)
        rs = tradeoff_vs_rounds(cfg, rounds=(1, 4))
        base = run_variance_experiment(cfg)
        p1 = plot_variance_vs_rounds(rs, str(tmp_path / "t.png"), base)
        p2 = plot_variance_vs_wallclock(rs, str(tmp_path / "w.png"))
        ps = tradeoff_vs_pairs(cfg, pairs=(100, 1000))
        p3 = plot_variance_vs_pairs(ps, str(tmp_path / "b.png"))
        from tuplewise_tpu.harness.figures import plot_variance_vs_workers

        ws = [
            run_variance_experiment(
                dataclasses.replace(cfg, scheme="local", n_workers=N)
            )
            for N in (2, 8)
        ]
        p4 = plot_variance_vs_workers(
            ws, str(tmp_path / "n.png"), baseline=base,
            theory=[(2, 1e-4), (8, 2e-4)],
        )
        import os

        for p in (p1, p2, p3, p4):
            assert os.path.getsize(p) > 1000


class TestMeshMC:
    """Mesh-native on-device Monte-Carlo [VERDICT r1 next #4]."""

    def _needs_mesh(self):
        import jax

        if jax.device_count() < 8:
            pytest.skip("needs 8 virtual devices")

    @pytest.mark.parametrize("scheme", ["complete", "local"])
    def test_pallas_branches_interpret_parity(self, scheme, monkeypatch):
        """TUPLEWISE_HARNESS_PALLAS=interpret drives the mesh runner's
        TPU-only Pallas branches (ring stats + local means) on the CPU
        mesh; estimates must match the XLA scan path."""
        self._needs_mesh()
        cfg = VarianceConfig(
            n_pos=256, n_neg=256, n_workers=8, n_reps=4,
            backend="mesh", scheme=scheme,
        )
        monkeypatch.setenv("TUPLEWISE_HARNESS_PALLAS", "off")
        xla = run_variance_experiment(cfg)
        monkeypatch.setenv("TUPLEWISE_HARNESS_PALLAS", "interpret")
        pal = run_variance_experiment(cfg)
        assert abs(pal["mean"] - xla["mean"]) < 1e-6

    @pytest.mark.parametrize("scheme", ["complete", "local"])
    def test_triplet_factorization_interpret_parity(self, scheme,
                                                    monkeypatch):
        """The Pallas distance factorization for degree-3 [VERDICT r3
        next #3] runs on the CPU mesh under the interpret override and
        must match the XLA triple tile scan."""
        self._needs_mesh()
        cfg = VarianceConfig(
            kernel="triplet_indicator", dim=3, n_pos=48, n_neg=40,
            n_workers=8, n_reps=2, backend="mesh", scheme=scheme,
        )
        monkeypatch.setenv("TUPLEWISE_HARNESS_PALLAS", "off")
        xla = run_variance_experiment(cfg)
        monkeypatch.setenv("TUPLEWISE_HARNESS_PALLAS", "interpret")
        pal = run_variance_experiment(cfg)
        assert abs(pal["mean"] - xla["mean"]) < 1e-6

    @pytest.mark.parametrize(
        "scheme", ["complete", "local", "repartitioned", "incomplete"]
    )
    def test_unbiased_and_on_device(self, scheme):
        self._needs_mesh()
        cfg = VarianceConfig(
            backend="mesh", scheme=scheme, n_pos=512, n_neg=512,
            n_workers=8, n_rounds=2, n_pairs=4096, n_reps=64,
        )
        r = run_variance_experiment(cfg)
        assert r["vmapped"], "mesh config fell back to the host loop"
        assert abs(r["mean"] - true_gaussian_auc(1.0)) < (
            5 * r["std_error"] + 1e-3
        )

    def test_variance_matches_jax_backend(self):
        """Mesh-native MC must draw from the same estimate distribution
        as the single-device vmapped path (same scheme semantics)."""
        self._needs_mesh()
        base = dict(scheme="local", n_pos=512, n_neg=512,
                    n_workers=8, n_reps=300)
        rm = run_variance_experiment(VarianceConfig(backend="mesh", **base))
        rj = run_variance_experiment(VarianceConfig(backend="jax", **base))
        # variance ratio CI: var estimates over M reps fluctuate ~sqrt(2/M)
        ratio = rm["variance"] / rj["variance"]
        assert 0.5 < ratio < 2.0, (rm["variance"], rj["variance"])

    @pytest.mark.parametrize(
        "scheme", ["complete", "local", "repartitioned", "incomplete"]
    )
    def test_ragged_sizes_stay_on_device(self, scheme):
        """N that does not divide n runs mask-aware on device now
        [VERDICT r2 next #5] — no host-loop fallback, still unbiased."""
        self._needs_mesh()
        cfg = VarianceConfig(
            backend="mesh", scheme=scheme, n_pos=515, n_neg=509,
            n_workers=8, n_rounds=2, n_pairs=4096, n_reps=48,
        )
        r = run_variance_experiment(cfg)
        assert r["vmapped"], "ragged mesh config fell back to host loop"
        assert abs(r["mean"] - true_gaussian_auc(1.0)) < (
            5 * r["std_error"] + 1e-3
        )

    @pytest.mark.parametrize(
        "scheme", ["complete", "local", "repartitioned", "incomplete"]
    )
    def test_scatter_feature_kernel_on_device(self, scheme):
        """One-sample feature kernels (scatter) run mesh-native with
        global-id pair exclusion [VERDICT r2 next #5]: the mean must
        match the population value E h = E||X-X'||^2 / 2 = dim for unit
        Gaussians (dim=1 here; the class shift cancels in
        differences)."""
        self._needs_mesh()
        cfg = VarianceConfig(
            kernel="scatter", backend="mesh", scheme=scheme,
            n_pos=512, n_neg=512, n_workers=8, n_rounds=2,
            n_pairs=4096, n_reps=48,
        )
        r = run_variance_experiment(cfg)
        assert r["vmapped"], "scatter mesh config fell back to host loop"
        assert abs(r["mean"] - 1.0) < 5 * r["std_error"] + 0.02

    @pytest.mark.parametrize("design", ["swor", "bernoulli"])
    def test_designed_incomplete_on_mesh(self, design):
        """Host-designed distinct tuple sets run mesh-native per rep
        (sharded [N, per] index blocks, cross-shard regather, psum'd
        weighted mean) [VERDICT r3 next #4]."""
        self._needs_mesh()
        cfg = VarianceConfig(
            backend="mesh", scheme="incomplete", n_pos=96, n_neg=96,
            n_workers=8, n_pairs=1_000, design=design, n_reps=400,
        )
        r = run_variance_experiment(cfg)
        assert r["vmapped"], "designed mesh config fell back to host loop"
        assert abs(r["mean"] - r["population_value"]) < 5 * r["std_error"]

    def test_designed_one_sample_on_mesh(self):
        """One-sample designed sets (scatter, off-diagonal encoding)
        stay mesh-native; mean matches E||X-X'||^2 / 2 = dim = 1."""
        self._needs_mesh()
        cfg = VarianceConfig(
            kernel="scatter", backend="mesh", scheme="incomplete",
            n_pos=96, n_neg=96, n_workers=8, n_pairs=800,
            design="swor", n_reps=64,
        )
        r = run_variance_experiment(cfg)
        assert r["vmapped"]
        assert abs(r["mean"] - 1.0) < 5 * r["std_error"] + 0.02

    def test_designed_triplet_on_mesh(self):
        """Degree-3 designed sets (swor) run mesh-native; the mean must
        agree with the numpy oracle's complete value on a
        same-distribution draw within MC error."""
        self._needs_mesh()
        cfg = VarianceConfig(
            kernel="triplet_indicator", dim=3, n_pos=64, n_neg=48,
            n_workers=8, backend="mesh", scheme="incomplete",
            n_pairs=600, design="swor", n_reps=64,
        )
        r = run_variance_experiment(cfg)
        assert r["vmapped"]
        from tuplewise_tpu.data import make_gaussians as mg
        from tuplewise_tpu.estimators.estimator import Estimator

        X, Y = mg(64, 48, 3, 1.0, seed=123)
        ref = Estimator("triplet_indicator", backend="numpy").complete(X, Y)
        assert abs(r["mean"] - ref) < 5 * r["std_error"] + 0.05

    def test_scatter_matches_host_loop_distribution(self):
        """Mesh-native scatter draws from the same distribution as the
        host-loop mesh Estimator (same semantics, different fold
        chains): means agree within combined MC error."""
        self._needs_mesh()
        cfg = VarianceConfig(
            kernel="scatter", backend="mesh", scheme="complete",
            n_pos=160, n_neg=160, n_workers=8, n_reps=24,
        )
        r_dev = run_variance_experiment(cfg)
        assert r_dev["vmapped"]
        # host loop over the public Estimator API (the old fallback)
        from tuplewise_tpu.estimators.estimator import Estimator
        from tuplewise_tpu.harness.variance import _estimate_once

        est = Estimator("scatter", backend="mesh", n_workers=8)
        host = [
            _estimate_once(est, cfg, rep) for rep in range(24)
        ]
        se = (r_dev["variance"] / 24 + np.var(host, ddof=1) / 24) ** 0.5
        assert abs(r_dev["mean"] - np.mean(host)) < 5 * se + 1e-3

    @pytest.mark.parametrize(
        "scheme", ["complete", "local", "repartitioned", "incomplete"]
    )
    def test_triplet_kernel_on_device(self, scheme):
        """Degree-3 kernels run mesh-native too (double ring for
        complete, global-id anchor/positive exclusion): the kernel-kind
        matrix has no host-loop fallback left. Mean must match the
        numpy complete statistic on the same distribution within MC
        error."""
        self._needs_mesh()
        cfg = VarianceConfig(
            kernel="triplet_indicator", backend="mesh", scheme=scheme,
            n_pos=64, n_neg=56, dim=3, n_workers=8, n_rounds=2,
            n_pairs=4096, n_reps=24,
        )
        r = run_variance_experiment(cfg)
        assert r["vmapped"], "triplet mesh config fell back to host loop"
        # population reference: numpy complete on a large fresh draw
        from tuplewise_tpu.data import make_gaussians
        from tuplewise_tpu.estimators.estimator import Estimator

        X, Y = make_gaussians(400, 400, dim=3, separation=1.0, seed=99)
        ref = Estimator("triplet_indicator", backend="numpy").complete(X, Y)
        assert abs(r["mean"] - ref) < 5 * r["std_error"] + 0.02

    def test_2d_mesh_runner(self):
        """A 2-D (dcn x ici) mesh compiles and reproduces the 1-D
        runner's estimates distributionally [VERDICT r2 next #5]; the
        complete scheme is deterministic given data, so means match the
        flat-mesh complete at matched n within MC error."""
        self._needs_mesh()
        import jax.numpy as jnp

        from tuplewise_tpu.harness.mesh_mc import make_mesh_mc_runner
        from tuplewise_tpu.parallel.mesh import make_mesh_2d

        cfg = VarianceConfig(
            backend="mesh", scheme="complete", n_pos=512, n_neg=512,
            n_workers=8, n_reps=16,
        )
        run2d = make_mesh_mc_runner(cfg, mesh=make_mesh_2d(2, 4))
        assert run2d is not None, "2-D mesh returned no runner"
        ests = np.asarray(run2d(jnp.arange(16)))
        se = ests.std(ddof=1) / 4
        assert abs(ests.mean() - true_gaussian_auc(1.0)) < 5 * se + 1e-3

    def test_2d_mesh_ragged_local(self):
        self._needs_mesh()
        import jax.numpy as jnp

        from tuplewise_tpu.harness.mesh_mc import make_mesh_mc_runner
        from tuplewise_tpu.parallel.mesh import make_mesh_2d

        cfg = VarianceConfig(
            backend="mesh", scheme="local", n_pos=515, n_neg=509,
            n_workers=8, n_reps=16,
        )
        run2d = make_mesh_mc_runner(cfg, mesh=make_mesh_2d(4, 2))
        assert run2d is not None
        ests = np.asarray(run2d(jnp.arange(16)))
        se = ests.std(ddof=1) / 4
        assert abs(ests.mean() - true_gaussian_auc(1.0)) < 5 * se + 1e-3


class TestWorkersSweep:
    def test_tradeoff_vs_workers_shape_and_cli(self, tmp_path):
        """Sweep returns one result per N and the variance in the
        small-block regime exceeds the large-block one; the CLI
        subcommand emits the same JSON."""
        from tuplewise_tpu.harness import tradeoff_vs_workers

        cfg = VarianceConfig(n_pos=96, n_neg=96, n_reps=150)
        rs = tradeoff_vs_workers(cfg, workers=(2, 24))
        assert [r["config"]["n_workers"] for r in rs] == [2, 24]
        assert all(r["config"]["scheme"] == "local" for r in rs)
        # m=48 -> near-floor; m=4 -> visibly inflated (~+25%)
        assert rs[1]["variance"] > rs[0]["variance"]

        out = subprocess.run(
            [sys.executable, "-m", "tuplewise_tpu.harness.cli",
             "tradeoff-workers", "--n-pos", "64", "--n-neg", "64",
             "--n-reps", "8", "--workers", "2", "8",
             "--out", str(tmp_path / "w.jsonl")],
            capture_output=True, text=True, check=True,
        )
        lines = [json.loads(x) for x in out.stdout.splitlines() if x.strip()]
        assert [r["config"]["n_workers"] for r in lines] == [2, 8]
        assert (tmp_path / "w.jsonl").exists()

    def test_tradeoff_vs_workers_rejects_oversubscription(self):
        from tuplewise_tpu.harness import tradeoff_vs_workers

        cfg = VarianceConfig(n_pos=96, n_neg=96, n_reps=4)
        # sweep validates up-front: the late bad N fails BEFORE any
        # compute is spent on the early good ones
        with pytest.raises(ValueError, match="per-class sample size"):
            tradeoff_vs_workers(cfg, workers=(2, 128))
        # every entry point is guarded, not just the sweep wrapper
        with pytest.raises(ValueError, match="per-class sample size"):
            run_variance_experiment(
                dataclasses.replace(cfg, scheme="local", n_workers=128)
            )


def test_committed_results_pass_statistical_audit(tmp_path):
    """Every committed results/*.jsonl harness row must sit within
    |z| <= 4 of its Hoeffding closed form (scripts/stat_check.py) —
    the theory-vs-artifact regression gate. Writes its report to
    tmp_path so test runs never dirty the committed artifact."""
    import importlib.util
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if not os.path.isdir(os.path.join(repo, "results")):
        pytest.skip("no committed results directory")
    spec = importlib.util.spec_from_file_location(
        "stat_check", os.path.join(repo, "scripts", "stat_check.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main(out=str(tmp_path / "stat_check.txt")) == 0


def test_frontier_figure(tmp_path):
    from tuplewise_tpu.harness.figures import plot_frontier

    cfg = VarianceConfig(n_pos=128, n_neg=128, n_reps=20)
    comp = run_variance_experiment(cfg)
    inc = tradeoff_vs_pairs(cfg, pairs=(100, 1000))
    p = plot_frontier(
        {"complete": [comp], "incomplete": inc}, str(tmp_path / "f.png")
    )
    import os

    assert os.path.getsize(p) > 1000
