"""bench.py output contract: the driver parses stdout as EXACTLY one
JSON line carrying the headline record [ISSUE 1 satellite].

Runs the streaming mode (tiny n — the batch mode's n=2^20 kernel
benchmark is not a unit-test-sized workload); diagnostics must stay on
stderr.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_streaming_bench_emits_one_json_line():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # the heavy side cells (delta bytes, multi-tenant, incremental
    # fleet, count kernel) are disabled here: this test pins the
    # STDOUT CONTRACT of the headline streaming record, and every
    # cell's substance has its own dedicated suite
    # (test_sharded_index / test_tenancy / test_fleet_incremental /
    # test_pallas_counts) plus the CI smokes — re-running them in a
    # subprocess cost ~2 minutes of tier-1 budget for zero added
    # coverage [ISSUE 10 satellite]
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--streaming",
         "--n-events", "400", "--baseline-events", "100",
         "--max-batch", "32", "--delta-bench-n", "0",
         "--tenant-bench-n", "0", "--fleet-bench-n", "0",
         "--kernel-bench-n", "0", "--controller-bench-n", "0"],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"stdout must be exactly one line: {lines}"
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec, f"missing {key!r} in {rec}"
    assert rec["metric"] == "events/sec"
    assert rec["unit"] == "events/s"
    assert rec["value"] > 0
    assert rec["vs_baseline"] > 0
    # parity guardrail rides in the same record
    assert rec["auc_abs_err"] < 1e-6
    # per-event insert-latency percentiles + the sync-compaction
    # comparison [ISSUE 2 satellite]
    for key in ("insert_latency_p50_ms", "insert_latency_p95_ms",
                "insert_latency_p99_ms", "sync_compact_insert_p99_ms",
                "p99_insert_vs_sync_compact"):
        assert key in rec, f"missing {key!r} in {rec}"
    assert rec["insert_latency_p99_ms"] > 0
    assert rec["bg_compact"] is True
