"""Mesh-sharded serving index [ISSUE 2 tentpole; delta compaction
ISSUE 5].

The contract: sharding the base runs over an S-device mesh (per-shard
jitted searchsorted + psum'd integer win counts) changes WHERE counts
are computed, never their values — wins2, every prefix AUC, and every
fractional rank are bit-identical to the single-host index (and match
the NumPy midrank oracle) at mesh sizes 1, 2, and 4, on the 8
virtual-CPU-device test platform.

Delta compaction [ISSUE 5] extends the same contract to the tiered
engine: minor compactions (delta run placement), tombstone-multiset
subtraction, on-mesh major merges, and the host fallback must all be
invisible to the statistic under randomized insert/evict/compact
schedules — and the major merge must actually run ON the mesh (zero
host→device bytes) when S >= 2.
"""

import numpy as np
import pytest

from tuplewise_tpu.models.metrics import auc_score
from tuplewise_tpu.serving import ExactAucIndex, MicroBatchEngine
from tuplewise_tpu.serving.replay import make_stream


def _stream(n, seed=7, pos_frac=0.45):
    scores, labels = make_stream(n, pos_frac=pos_frac, separation=1.0,
                                 seed=seed)
    return scores.astype(np.float32), labels


def _oracle(scores, labels):
    pos, neg = scores[labels], scores[~labels]
    if len(pos) == 0 or len(neg) == 0:
        return None
    return auc_score(pos.astype(np.float64), neg.astype(np.float64))


@pytest.mark.parametrize("shards", [1, 2, 4])
class TestShardedBitParity:
    def test_prefix_wins2_bit_identical_to_single_host(self, shards):
        scores, labels = _stream(1500)
        sharded = ExactAucIndex(engine="jax", compact_every=96,
                                shards=shards)
        single = ExactAucIndex(engine="jax", compact_every=96)
        off = 0
        for c in (1, 2, 50, 96, 97, 200, 513, 777, 1024, 1500):
            sharded.insert_batch(scores[off:c], labels[off:c])
            single.insert_batch(scores[off:c], labels[off:c])
            off = c
            # INTEGER state equality — stronger than float tolerance
            assert sharded._wins2 == single._wins2, c
            assert sharded.auc() == single.auc(), c
            oracle = _oracle(scores[:c], labels[:c])
            if oracle is not None:
                assert sharded.auc() == pytest.approx(oracle, abs=1e-6)
        assert sharded.n_compactions > 0

    def test_windowed_eviction_parity(self, shards):
        scores, labels = _stream(1200, seed=5)
        W = 300
        sharded = ExactAucIndex(engine="jax", window=W, compact_every=48,
                                shards=shards)
        single = ExactAucIndex(engine="jax", window=W, compact_every=48)
        for i in range(0, 1200, 29):
            k = min(i + 29, 1200)
            sharded.insert_batch(scores[i:k], labels[i:k])
            single.insert_batch(scores[i:k], labels[i:k])
            assert sharded._wins2 == single._wins2, k
            assert sharded.auc() == single.auc(), k
        tail_s, tail_l = scores[-W:], labels[-W:]
        assert sharded.auc() == pytest.approx(_oracle(tail_s, tail_l),
                                              abs=1e-6)

    def test_score_batch_bit_identical(self, shards):
        scores, labels = _stream(900, seed=3)
        sharded = ExactAucIndex(engine="jax", compact_every=64,
                                shards=shards)
        single = ExactAucIndex(engine="jax", compact_every=64)
        sharded.insert_batch(scores, labels)
        single.insert_batch(scores, labels)
        q = np.linspace(-3, 3, 37, dtype=np.float32)
        np.testing.assert_array_equal(sharded.score_batch(q),
                                      single.score_batch(q))


class TestShardedConfig:
    def test_rejects_numpy_engine(self):
        with pytest.raises(ValueError, match="engine='jax'"):
            ExactAucIndex(engine="numpy", shards=2)

    def test_existing_mesh_accepted(self):
        from tuplewise_tpu.parallel.mesh import make_mesh

        idx = ExactAucIndex(engine="jax", mesh=make_mesh(2),
                            compact_every=32)
        scores, labels = _stream(200, seed=9)
        idx.insert_batch(scores, labels)
        assert idx.shards == 2
        assert idx.auc() == pytest.approx(_oracle(scores, labels),
                                          abs=1e-6)

    def test_state_reports_shards(self):
        idx = ExactAucIndex(engine="jax", shards=2)
        assert idx.state()["shards"] == 2
        assert ExactAucIndex(engine="jax").state()["shards"] is None


class TestEngineIntegration:
    def test_mesh_shards_through_the_engine(self):
        scores, labels = _stream(800, seed=13)
        with MicroBatchEngine(mesh_shards=2, compact_every=64,
                              policy="block") as eng:
            eng.insert(scores, labels).result(30.0)
            snap = eng.flush()
        assert snap["index"]["shards"] == 2
        assert snap["auc_exact"] == pytest.approx(
            _oracle(scores, labels), abs=1e-6)


@pytest.mark.parametrize("shards", [1, 2, 4])
class TestDeltaCompaction:
    """[ISSUE 5] The tiered compaction engine — delta runs, tombstone
    multiset, major merges — is invisible to the statistic."""

    def test_randomized_insert_evict_compact_schedule(self, shards):
        """Randomized batches against a sliding window (evictions →
        tombstones), interleaved forced full compactions, and
        auto-triggered minor/major tiers: wins2 and AUC bit-identical
        to the single-host index at every step."""
        rng = np.random.default_rng(shards)
        scores, labels = _stream(2200, seed=40 + shards)
        delta = ExactAucIndex(engine="jax", compact_every=48,
                              shards=shards, window=500,
                              delta_fraction=0.25, max_delta_runs=3)
        single = ExactAucIndex(engine="jax", compact_every=48,
                               window=500)
        off = 0
        saw_delta = False
        while off < len(scores):
            k = min(off + int(rng.integers(1, 70)), len(scores))
            delta.insert_batch(scores[off:k], labels[off:k])
            single.insert_batch(scores[off:k], labels[off:k])
            off = k
            assert delta._wins2 == single._wins2, off
            assert delta.auc() == single.auc(), off
            saw_delta = saw_delta or delta.state()["delta_events"] > 0
            if rng.random() < 0.05:
                delta.compact()     # full consolidation mid-stream
                assert delta._wins2 == single._wins2, off
        st = delta.state()
        assert saw_delta, "schedule never produced a delta run"
        assert st["n_major_merges"] > 0, "no major merge triggered"
        assert delta.n_evicted > 0
        tail_s, tail_l = scores[-500:], labels[-500:]
        assert delta.auc() == pytest.approx(_oracle(tail_s, tail_l),
                                            abs=1e-6)
        q = np.linspace(-3, 3, 29, dtype=np.float32)
        np.testing.assert_array_equal(delta.score_batch(q),
                                      single.score_batch(q))

    def test_tombstones_subtract_exactly(self, shards):
        """Window small vs compact_every: evictions outpace inserts'
        compactions, so the tombstone multiset (and its overflow full
        rebuild) carries the parity."""
        scores, labels = _stream(1500, seed=60 + shards)
        delta = ExactAucIndex(engine="jax", compact_every=32,
                              shards=shards, window=300,
                              delta_fraction=0.5, max_delta_runs=4)
        single = ExactAucIndex(engine="jax", compact_every=32,
                               window=300)
        for i in range(0, 1500, 37):
            k = min(i + 37, 1500)
            delta.insert_batch(scores[i:k], labels[i:k])
            single.insert_batch(scores[i:k], labels[i:k])
            assert delta._wins2 == single._wins2, k
        assert delta.auc() == pytest.approx(
            _oracle(scores[-300:], labels[-300:]), abs=1e-6)

    def test_host_merge_mode_disables_tiers(self, shards):
        """delta_fraction=0 restores the PR 2 path: no delta runs, no
        majors, same statistic."""
        scores, labels = _stream(600, seed=70 + shards)
        idx = ExactAucIndex(engine="jax", compact_every=64,
                            shards=shards, delta_fraction=0.0)
        single = ExactAucIndex(engine="jax", compact_every=64)
        idx.insert_batch(scores, labels)
        single.insert_batch(scores, labels)
        st = idx.state()
        assert not st["delta_compact"]
        assert st["n_major_merges"] == 0 and st["delta_events"] == 0
        assert idx._wins2 == single._wins2


class TestOnMeshMajorMerge:
    """[ISSUE 5] The major merge must actually run on the mesh at
    S >= 2 — zero host→device bytes — and produce exactly the
    placement ``place_base`` would."""

    @pytest.mark.parametrize("shards", [2, 4])
    def test_merge_kernel_matches_canonical_placement(self, shards):
        from tuplewise_tpu.parallel.mesh import make_mesh
        from tuplewise_tpu.parallel.sharded_counts import (
            place_base, plan_major_merge, sharded_major_merge,
        )

        rng = np.random.default_rng(shards)
        mesh = make_mesh(shards)
        base = np.sort(rng.standard_normal(4001).astype(np.float32))
        delta = np.sort(rng.standard_normal(700).astype(np.float32))
        base_dev, cap, _ = place_base(mesh, base, np.float32)
        delta_dev, dcap, _ = place_base(mesh, delta, np.float32)
        plan = plan_major_merge(base, delta, shards)
        assert plan.ok
        out, cap_out = sharded_major_merge(
            mesh, base_dev, cap, ((delta_dev, dcap),), plan)
        merged = np.sort(np.concatenate([base, delta]))
        expect_dev, expect_cap, _ = place_base(mesh, merged, np.float32)
        assert cap_out == expect_cap
        np.testing.assert_array_equal(np.asarray(out),
                                      np.asarray(expect_dev))

    def test_on_mesh_path_taken_and_bytes_saved(self):
        """At S=2 with spread data the plan fits the one-hop exchange:
        majors run on-mesh (no fallback) and credit bytes_h2d_saved."""
        scores, labels = _stream(1600, seed=5)
        idx = ExactAucIndex(engine="jax", compact_every=64, shards=2,
                            delta_fraction=0.25, max_delta_runs=3)
        idx.insert_batch(scores, labels)
        for i in range(3):   # keep feeding to cross several majors
            idx.insert_batch(scores[i::3], labels[i::3])
        snap = idx.metrics.snapshot()
        assert idx.n_major_merges > 0
        assert snap["major_merge_fallbacks"]["value"] < idx.n_major_merges
        assert snap["bytes_h2d_saved"]["value"] > 0

    def test_chaos_major_merge_falls_back_to_host(self):
        """An injected major_merge fault exercises the host fallback:
        the statistic is untouched and the fallback is counted."""
        from tuplewise_tpu.testing.chaos import FaultInjector

        chaos = FaultInjector.from_spec(
            {"faults": [{"point": "major_merge", "on_call": 1,
                         "action": "error"}]})
        scores, labels = _stream(1200, seed=6)
        idx = ExactAucIndex(engine="jax", compact_every=64, shards=2,
                            delta_fraction=0.25, max_delta_runs=3,
                            chaos=chaos)
        single = ExactAucIndex(engine="jax", compact_every=64)
        # batched feed: the FIRST major folds into an empty base (host
        # path, no on-mesh attempt); later majors hit the kernel and
        # the scheduled fault
        for i in range(0, 1200, 97):
            k = min(i + 97, 1200)
            idx.insert_batch(scores[i:k], labels[i:k])
            single.insert_batch(scores[i:k], labels[i:k])
            assert idx._wins2 == single._wins2, k
        assert idx._wins2 == single._wins2
        assert idx.metrics.snapshot()["major_merge_fallbacks"][
            "value"] >= 1
        assert idx.last_major_merge_error is not None
        assert chaos.snapshot()["fired"].get("major_merge") == 1


class TestPlacementReuse:
    """[ISSUE 5 satellite] place_base re-ships only changed rows when
    the bucket geometry is unchanged, and counts the saved bytes."""

    def test_tail_growth_ships_one_row(self):
        from tuplewise_tpu.parallel.mesh import make_mesh
        from tuplewise_tpu.parallel.sharded_counts import (
            place_base, sharded_counts,
        )
        from tuplewise_tpu.utils.profiling import MetricsRegistry

        mesh = make_mesh(4)
        m = MetricsRegistry()
        rng = np.random.default_rng(0)
        base = np.sort(rng.standard_normal(999).astype(np.float32))
        dev, cap, first = place_base(mesh, base, np.float32, metrics=m)
        assert first == 4 * cap * 4
        # append one value above the max: per (=250) and cap are
        # unchanged, rows 0..2 identical — only the tail row ships
        ext = np.concatenate(
            [base, np.asarray([base[-1] + 1.0], dtype=np.float32)])
        dev2, cap2, shipped = place_base(mesh, ext, np.float32,
                                         prev=(base, dev, cap),
                                         metrics=m)
        assert cap2 == cap and shipped == cap * 4
        assert m.snapshot()["bytes_h2d_saved"]["value"] == 3 * cap * 4
        q = rng.standard_normal(17).astype(np.float32)
        less, leq = sharded_counts(mesh, dev2, cap2, q, np.float32)
        np.testing.assert_array_equal(
            less, np.searchsorted(ext, q, side="left"))
        np.testing.assert_array_equal(
            leq, np.searchsorted(ext, q, side="right"))

    def test_identical_replacement_ships_nothing(self):
        from tuplewise_tpu.parallel.mesh import make_mesh
        from tuplewise_tpu.parallel.sharded_counts import place_base

        mesh = make_mesh(2)
        base = np.sort(np.random.default_rng(1).standard_normal(
            500).astype(np.float32))
        dev, cap, _ = place_base(mesh, base, np.float32)
        dev2, cap2, shipped = place_base(mesh, base, np.float32,
                                         prev=(base, dev, cap))
        assert shipped == 0 and dev2 is dev and cap2 == cap
