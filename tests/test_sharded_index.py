"""Mesh-sharded serving index [ISSUE 2 tentpole].

The contract: sharding the base runs over an S-device mesh (per-shard
jitted searchsorted + psum'd integer win counts) changes WHERE counts
are computed, never their values — wins2, every prefix AUC, and every
fractional rank are bit-identical to the single-host index (and match
the NumPy midrank oracle) at mesh sizes 1, 2, and 4, on the 8
virtual-CPU-device test platform.
"""

import numpy as np
import pytest

from tuplewise_tpu.models.metrics import auc_score
from tuplewise_tpu.serving import ExactAucIndex, MicroBatchEngine
from tuplewise_tpu.serving.replay import make_stream


def _stream(n, seed=7, pos_frac=0.45):
    scores, labels = make_stream(n, pos_frac=pos_frac, separation=1.0,
                                 seed=seed)
    return scores.astype(np.float32), labels


def _oracle(scores, labels):
    pos, neg = scores[labels], scores[~labels]
    if len(pos) == 0 or len(neg) == 0:
        return None
    return auc_score(pos.astype(np.float64), neg.astype(np.float64))


@pytest.mark.parametrize("shards", [1, 2, 4])
class TestShardedBitParity:
    def test_prefix_wins2_bit_identical_to_single_host(self, shards):
        scores, labels = _stream(1500)
        sharded = ExactAucIndex(engine="jax", compact_every=96,
                                shards=shards)
        single = ExactAucIndex(engine="jax", compact_every=96)
        off = 0
        for c in (1, 2, 50, 96, 97, 200, 513, 777, 1024, 1500):
            sharded.insert_batch(scores[off:c], labels[off:c])
            single.insert_batch(scores[off:c], labels[off:c])
            off = c
            # INTEGER state equality — stronger than float tolerance
            assert sharded._wins2 == single._wins2, c
            assert sharded.auc() == single.auc(), c
            oracle = _oracle(scores[:c], labels[:c])
            if oracle is not None:
                assert sharded.auc() == pytest.approx(oracle, abs=1e-6)
        assert sharded.n_compactions > 0

    def test_windowed_eviction_parity(self, shards):
        scores, labels = _stream(1200, seed=5)
        W = 300
        sharded = ExactAucIndex(engine="jax", window=W, compact_every=48,
                                shards=shards)
        single = ExactAucIndex(engine="jax", window=W, compact_every=48)
        for i in range(0, 1200, 29):
            k = min(i + 29, 1200)
            sharded.insert_batch(scores[i:k], labels[i:k])
            single.insert_batch(scores[i:k], labels[i:k])
            assert sharded._wins2 == single._wins2, k
            assert sharded.auc() == single.auc(), k
        tail_s, tail_l = scores[-W:], labels[-W:]
        assert sharded.auc() == pytest.approx(_oracle(tail_s, tail_l),
                                              abs=1e-6)

    def test_score_batch_bit_identical(self, shards):
        scores, labels = _stream(900, seed=3)
        sharded = ExactAucIndex(engine="jax", compact_every=64,
                                shards=shards)
        single = ExactAucIndex(engine="jax", compact_every=64)
        sharded.insert_batch(scores, labels)
        single.insert_batch(scores, labels)
        q = np.linspace(-3, 3, 37, dtype=np.float32)
        np.testing.assert_array_equal(sharded.score_batch(q),
                                      single.score_batch(q))


class TestShardedConfig:
    def test_rejects_numpy_engine(self):
        with pytest.raises(ValueError, match="engine='jax'"):
            ExactAucIndex(engine="numpy", shards=2)

    def test_existing_mesh_accepted(self):
        from tuplewise_tpu.parallel.mesh import make_mesh

        idx = ExactAucIndex(engine="jax", mesh=make_mesh(2),
                            compact_every=32)
        scores, labels = _stream(200, seed=9)
        idx.insert_batch(scores, labels)
        assert idx.shards == 2
        assert idx.auc() == pytest.approx(_oracle(scores, labels),
                                          abs=1e-6)

    def test_state_reports_shards(self):
        idx = ExactAucIndex(engine="jax", shards=2)
        assert idx.state()["shards"] == 2
        assert ExactAucIndex(engine="jax").state()["shards"] is None


class TestEngineIntegration:
    def test_mesh_shards_through_the_engine(self):
        scores, labels = _stream(800, seed=13)
        with MicroBatchEngine(mesh_shards=2, compact_every=64,
                              policy="block") as eng:
            eng.insert(scores, labels).result(30.0)
            snap = eng.flush()
        assert snap["index"]["shards"] == 2
        assert snap["auc_exact"] == pytest.approx(
            _oracle(scores, labels), abs=1e-6)
