"""Pallas-fused serving counts [ISSUE 10]: kernel-vs-XLA bit-exact
parity (integers, so parity is equality — not tolerance), the
one-invocation-per-micro-batch witness, automatic XLA fallback on
kernel failure, chaos heal with the kernel on, compile-cache growth
bounded by the (T_bucket, cap, q_bucket) ladder, and recovery
bit-identity. CPU runs execute the kernel through the Pallas
interpreter (TUPLEWISE_SERVING_PALLAS / count_kernel resolve to
interpret mode off-TPU)."""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from tuplewise_tpu.ops import pallas_counts
from tuplewise_tpu.parallel import sharded_counts as sc
from tuplewise_tpu.serving.index import ExactAucIndex
from tuplewise_tpu.serving.tenancy import TenantFleetIndex
from tuplewise_tpu.testing.chaos import FaultInjector


@pytest.fixture(autouse=True)
def _clean_kernel_state():
    """Each test starts with no latched-broken geometries and no
    forced-failure hook."""
    sc._KERNEL_BROKEN.clear()
    pallas_counts.FORCE_FAIL = False
    yield
    sc._KERNEL_BROKEN.clear()
    pallas_counts.FORCE_FAIL = False


def _stream(n, seed=0, sep=0.8, dup_every=13):
    rng = np.random.default_rng(seed)
    labels = rng.random(n) < 0.5
    scores = (rng.standard_normal(n) + sep * labels).astype(np.float32)
    # duplicated values exercise the left/right tie boundaries the
    # +inf-padded searchsorted contract depends on
    scores[::dup_every] = np.round(scores[::dup_every], 1)
    return scores, labels


class TestSignedPairCounts:
    """The dispatcher primitive against a NumPy searchsorted oracle."""

    def _oracle(self, runs, q):
        less = np.zeros(len(q), np.int64)
        leq = np.zeros(len(q), np.int64)
        for arr, sign in runs:
            less += sign * np.searchsorted(arr, q, side="left")
            leq += sign * np.searchsorted(arr, q, side="right")
        return less, leq

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_randomized_signed_parity_local(self, seed):
        rng = np.random.default_rng(seed)
        base = np.sort(rng.standard_normal(
            int(rng.integers(1, 400)))).astype(np.float32)
        delta = np.sort(rng.standard_normal(
            int(rng.integers(0, 60)))).astype(np.float32)
        tomb = np.sort(rng.choice(
            base, int(rng.integers(0, min(10, len(base)))),
            replace=False)).astype(np.float32)
        q = rng.standard_normal(int(rng.integers(1, 50))).astype(
            np.float32)
        q[: min(3, len(q))] = base[: min(3, len(q))]   # boundary ties
        runs = [(a, sc.next_bucket(len(a)), s)
                for a, s in ((base, 1), (delta, 1), (tomb, -1))
                if len(a)]
        less, leq, _, _ = sc.signed_pair_counts(
            None, runs, (), q, np.zeros(0, np.float32), np.float32,
            kernel=True)
        ol, oq = self._oracle(
            [(a, s) for a, s in ((base, 1), (delta, 1), (tomb, -1))
             if len(a)], q)
        assert np.array_equal(less, ol)
        assert np.array_equal(leq, oq)

    def test_two_query_sets_one_dispatch(self):
        rng = np.random.default_rng(7)
        neg = np.sort(rng.standard_normal(300)).astype(np.float32)
        pos = np.sort(rng.standard_normal(200)).astype(np.float32)
        qa = rng.standard_normal(17).astype(np.float32)
        qb = rng.standard_normal(9).astype(np.float32)
        la, lqa, lb, lqb = sc.signed_pair_counts(
            None, [(neg, 512, 1)], [(pos, 256, 1)], qa, qb,
            np.float32, kernel=True)
        assert np.array_equal(la, np.searchsorted(neg, qa, "left"))
        assert np.array_equal(lqa, np.searchsorted(neg, qa, "right"))
        assert np.array_equal(lb, np.searchsorted(pos, qb, "left"))
        assert np.array_equal(lqb, np.searchsorted(pos, qb, "right"))

    def test_xla_twin_matches_kernel(self):
        """The fallback target is bit-identical to the kernel — the
        property that makes the automatic fallback invisible."""
        rng = np.random.default_rng(11)
        base = np.sort(rng.standard_normal(500)).astype(np.float32)
        tomb = np.sort(rng.choice(base, 20, replace=False)).astype(
            np.float32)
        q = rng.standard_normal(40).astype(np.float32)
        runs = [(base, 512, 1), (tomb, 256, -1)]
        a = sc.signed_pair_counts(None, runs, (), q,
                                  np.zeros(0, np.float32), np.float32,
                                  kernel=True)
        b = sc.signed_pair_counts(None, runs, (), q,
                                  np.zeros(0, np.float32), np.float32,
                                  kernel=None)
        for x, y in zip(a, b):
            assert np.array_equal(x, y)


class TestIndexKernelParity:
    """ExactAucIndex(count_kernel=True) vs the stock XLA index —
    wins2/AUC/score ranks bit-identical at every step."""

    @pytest.mark.parametrize("shards,window", [
        (None, None), (None, 120), (1, 120), (2, None), (2, 150),
        (4, 100),
    ])
    def test_bit_identical_stream(self, shards, window):
        scores, labels = _stream(500, seed=3)
        kw = dict(engine="jax", compact_every=48, window=window)
        if shards is not None:
            kw.update(shards=shards, delta_fraction=0.25,
                      max_delta_runs=3)
        xla = ExactAucIndex(**kw)
        ker = ExactAucIndex(count_kernel=True, **kw)
        sizes = [67, 1, 33, 0, 128, 97, 174]
        i = 0
        for step, sz in enumerate(sizes * 2):
            j = min(i + sz, len(scores))
            xla.insert_batch(scores[i:j], labels[i:j])
            ker.insert_batch(scores[i:j], labels[i:j])
            i = j
            assert xla._wins2 == ker._wins2, (shards, window, step)
            assert xla.auc() == ker.auc()
            q = scores[max(0, j - 9):j]
            assert np.array_equal(
                np.nan_to_num(xla.score_batch(q)),
                np.nan_to_num(ker.score_batch(q)))
        # the kernel actually ran, and never fell back
        snap = ker.metrics.snapshot()
        assert snap["count_kernel_calls_total"]["value"] > 0
        assert snap["count_kernel_fallbacks_total"]["value"] == 0
        # and the multisets agree (tombstones included)
        for a, b in zip(xla.oracle_values(), ker.oracle_values()):
            assert np.array_equal(a, b)
        xla.close()
        ker.close()

    def test_full_compact_and_empty_cases(self):
        """compact() clears delta + tombstone runs (and the kernel's
        tombstone mirror); counting stays exact through empty-delta /
        empty-tombstone geometries."""
        scores, labels = _stream(400, seed=9)
        xla = ExactAucIndex(engine="jax", compact_every=32, window=90,
                            shards=2, max_delta_runs=2)
        ker = ExactAucIndex(engine="jax", compact_every=32, window=90,
                            shards=2, max_delta_runs=2,
                            count_kernel=True)
        for i in range(0, 400, 57):
            j = min(i + 57, 400)
            xla.insert_batch(scores[i:j], labels[i:j])
            ker.insert_batch(scores[i:j], labels[i:j])
            if i and i % 114 == 0:
                xla.compact()
                ker.compact()
            assert xla._wins2 == ker._wins2, i
        assert ker._pos.tomb_dev is None or len(ker._pos.tomb_run)
        xla.close()
        ker.close()

    def test_one_kernel_call_per_insert_batch(self):
        """The tentpole witness: one fused invocation per insert
        micro-batch — eviction queries ride the insert dispatch."""
        scores, labels = _stream(360, seed=13)
        ker = ExactAucIndex(engine="jax", compact_every=1000,
                            window=100, shards=2, count_kernel=True)
        # seed + place the base runs (before any placement exists, a
        # batch legitimately needs ZERO device dispatches — everything
        # counts against the host buffer)
        ker.insert_batch(scores[:45], labels[:45])
        ker.compact()
        before = ker.metrics.snapshot()[
            "count_kernel_calls_total"]["value"]
        n_batches = 0
        for i in range(45, 360, 45):
            ker.insert_batch(scores[i:i + 45], labels[i:i + 45])
            n_batches += 1
        calls = ker.metrics.snapshot()[
            "count_kernel_calls_total"]["value"] - before
        assert calls == n_batches, (calls, n_batches)
        ker.close()

    def test_env_off_kill_switch(self, monkeypatch):
        monkeypatch.setenv("TUPLEWISE_SERVING_PALLAS", "off")
        idx = ExactAucIndex(engine="jax", shards=2, count_kernel=True)
        scores, labels = _stream(80, seed=1)
        idx.insert_batch(scores, labels)
        assert idx.metrics.snapshot()[
            "count_kernel_calls_total"]["value"] == 0
        idx.close()

    def test_env_interpret_force_enables(self, monkeypatch):
        """env=interpret turns the kernel on even with the config flag
        off — how the existing suites run kernel-on wholesale."""
        monkeypatch.setenv("TUPLEWISE_SERVING_PALLAS", "interpret")
        idx = ExactAucIndex(engine="jax", shards=2, compact_every=32)
        monkeypatch.delenv("TUPLEWISE_SERVING_PALLAS")
        xla = ExactAucIndex(engine="jax", shards=2, compact_every=32)
        scores, labels = _stream(120, seed=2)
        for i in range(0, 120, 40):
            idx.insert_batch(scores[i:i + 40], labels[i:i + 40])
            xla.insert_batch(scores[i:i + 40], labels[i:i + 40])
        assert idx._wins2 == xla._wins2
        assert idx.metrics.snapshot()[
            "count_kernel_calls_total"]["value"] > 0
        assert xla.metrics.snapshot()[
            "count_kernel_calls_total"]["value"] == 0
        idx.close()
        xla.close()


class TestFallback:
    def test_forced_failure_falls_back_bit_identical(self):
        """A Mosaic failure (forced via the test hook) serves the XLA
        twin in the same call — results bit-identical, geometry
        latched, fallback counted."""
        scores, labels = _stream(300, seed=17)
        xla = ExactAucIndex(engine="jax", compact_every=64, shards=2)
        ker = ExactAucIndex(engine="jax", compact_every=64, shards=2,
                            count_kernel=True)
        pallas_counts.FORCE_FAIL = True
        for i in range(0, 300, 60):
            xla.insert_batch(scores[i:i + 60], labels[i:i + 60])
            ker.insert_batch(scores[i:i + 60], labels[i:i + 60])
            assert xla._wins2 == ker._wins2, i
        snap = ker.metrics.snapshot()
        assert snap["count_kernel_fallbacks_total"]["value"] > 0
        assert snap["count_kernel_calls_total"]["value"] == 0
        assert len(sc._KERNEL_BROKEN) > 0
        # latched: clearing the hook does NOT resurrect the broken
        # geometry — no per-request retry of a failed lowering
        pallas_counts.FORCE_FAIL = False
        fb = snap["count_kernel_fallbacks_total"]["value"]
        ker.insert_batch(scores[:60], labels[:60])
        xla.insert_batch(scores[:60], labels[:60])
        assert xla._wins2 == ker._wins2
        snap2 = ker.metrics.snapshot()
        assert snap2["count_kernel_fallbacks_total"]["value"] == fb
        xla.close()
        ker.close()

    def test_fleet_forced_failure_falls_back(self):
        pallas_counts.FORCE_FAIL = True
        fleet = TenantFleetIndex(compact_every=64, count_kernel=True)
        ref = TenantFleetIndex(compact_every=64)
        scores, labels = _stream(120, seed=19)
        for i in range(0, 120, 40):
            items = [("a", scores[i:i + 20], labels[i:i + 20]),
                     ("b", scores[i + 20:i + 40], labels[i + 20:i + 40])]
            fleet.apply_inserts(list(items))
            ref.apply_inserts(list(items))
        for t in ("a", "b"):
            assert fleet.wins2(t) == ref.wins2(t)
        snap = fleet.metrics.snapshot()
        assert snap["count_kernel_fallbacks_total"]["value"] > 0
        fleet.close()
        ref.close()


class TestChaosHealWithKernel:
    def test_device_loss_heals_bit_identical(self):
        """A device error mid-count with the kernel ON: probe →
        reshard over the survivor → re-place (base, delta AND the
        tombstone mirror) → retry; wins2 stays bit-identical to the
        unfaulted single-host index. The chaos fault must NOT latch
        the kernel as broken (the XLA twin fails the same way)."""
        scores, labels = _stream(700, seed=23)
        inj = FaultInjector.from_spec({"faults": [
            {"point": "sharded_count", "on_call": 7, "action": "error",
             "dropped": [1]}]})
        hurt = ExactAucIndex(engine="jax", compact_every=48, window=200,
                             shards=2, chaos=inj, count_kernel=True)
        plain = ExactAucIndex(engine="jax", compact_every=48,
                              window=200)
        for i in range(0, 700, 41):
            j = min(i + 41, 700)
            hurt.insert_batch(scores[i:j], labels[i:j])
            plain.insert_batch(scores[i:j], labels[i:j])
            assert hurt._wins2 == plain._wins2, i
        snap = hurt.metrics.snapshot()
        assert snap["reshard_events"]["value"] >= 1
        assert hurt.shards == 1           # shrank to the survivor
        assert snap["count_kernel_calls_total"]["value"] > 0
        assert not sc._KERNEL_BROKEN      # chaos never latches
        hurt.close()
        plain.close()

    def test_fleet_device_loss_heals_bit_identical(self):
        scores, labels = _stream(400, seed=29)
        inj = FaultInjector.from_spec({"faults": [
            {"point": "sharded_count", "on_call": 5, "action": "error",
             "dropped": [1]}]})
        hurt = TenantFleetIndex(compact_every=48, shards=2, chaos=inj,
                                count_kernel=True)
        ref = TenantFleetIndex(compact_every=48)
        for i in range(0, 400, 80):
            items = [("a", scores[i:i + 40], labels[i:i + 40]),
                     ("b", scores[i + 40:i + 80], labels[i + 40:i + 80])]
            hurt.apply_inserts(list(items))
            ref.apply_inserts(list(items))
        for t in ("a", "b"):
            assert hurt.wins2(t) == ref.wins2(t)
        assert hurt.metrics.snapshot()["reshard_events"]["value"] >= 1
        hurt.close()
        ref.close()


class TestFleetKernel:
    def test_parity_with_promotion_demotion_and_drop(self):
        """Whale promotion, demotion and a tenant drop (dirty-row slot
        reuse) mid-stream, kernel on — per-tenant wins2 bit-identical
        to dedicated single-tenant indexes throughout."""
        rng = np.random.default_rng(31)
        fleet = TenantFleetIndex(window=150, compact_every=24,
                                 shards=2, whale_threshold=100,
                                 count_kernel=True)
        singles = {}

        def push(tid, k):
            labels = rng.random(k) < 0.5
            scores = (rng.standard_normal(k) + 0.8 * labels).astype(
                np.float32)
            if tid not in singles:
                singles[tid] = ExactAucIndex(window=150,
                                             compact_every=24,
                                             engine="jax")
            singles[tid].insert_batch(scores, labels)
            return (tid, scores, labels)

        for step in range(16):
            items = [push("whale", 30)]
            items += [push(f"s{k}", 6) for k in range(4)]
            fleet.apply_inserts(items)
            if step == 8:
                fleet.drop("s0")
                singles.pop("s0").close()
            for tid, idx in singles.items():
                assert fleet.wins2(tid) == idx._wins2, (step, tid)
        assert fleet.is_whale("whale")
        fleet.demote("whale")
        items = [push("whale", 10)]
        fleet.apply_inserts(items)
        assert fleet.wins2("whale") == singles["whale"]._wins2
        snap = fleet.metrics.snapshot()
        assert snap["count_kernel_calls_total"]["value"] > 0
        assert snap["count_kernel_fallbacks_total"]["value"] == 0
        assert snap["fleet_whale_promotions"]["value"] >= 1
        fleet.close()
        for s in singles.values():
            s.close()

    @pytest.mark.parametrize("T", [1, 32, 256])
    def test_parity_across_fleet_sizes(self, T):
        """T=1/32/256 packs, kernel vs XLA fleet — wins2 bit-identical
        per tenant (the XLA fleet is itself pinned to independent
        single-tenant indexes elsewhere)."""
        rng = np.random.default_rng(59 + T)
        xla = TenantFleetIndex(compact_every=64, shards=2)
        ker = TenantFleetIndex(compact_every=64, shards=2,
                               count_kernel=True)
        for _ in range(3):
            items = []
            for t in range(T):
                k = 3
                labels = rng.random(k) < 0.5
                s = (rng.standard_normal(k) + 0.8 * labels).astype(
                    np.float32)
                items.append((f"t{t}", s, labels))
            xla.apply_inserts(list(items))
            ker.apply_inserts(list(items))
        assert ({t: xla.wins2(t) for t in xla.tenants()}
                == {t: ker.wins2(t) for t in ker.tenants()})
        snap = ker.metrics.snapshot()
        assert snap["count_kernel_calls_total"]["value"] > 0
        assert snap["count_kernel_fallbacks_total"]["value"] == 0
        xla.close()
        ker.close()

    def test_one_kernel_call_per_fleet_batch(self):
        fleet = TenantFleetIndex(compact_every=1000, count_kernel=True)
        scores, labels = _stream(200, seed=37)
        applies = 0
        for i in range(0, 200, 50):
            fleet.apply_inserts(
                [("a", scores[i:i + 25], labels[i:i + 25]),
                 ("b", scores[i + 25:i + 50], labels[i + 25:i + 50])])
            applies += 1
        snap = fleet.metrics.snapshot()
        assert snap["count_kernel_calls_total"]["value"] == applies
        assert snap["fleet_count_calls_total"]["value"] == applies
        fleet.close()


class TestCompileCacheLadder:
    def test_fleet_cache_invariant_to_live_tenant_count(self):
        """Compile-cache growth tracks the (T_bucket, cap, q_bucket)
        ladder, never the live tenant count: tenants 2 → 8 stay inside
        the T_bucket=8 floor (no new kernel entries); crossing to 9
        grows the ladder by exactly the new T_bucket geometry."""
        fleet = TenantFleetIndex(compact_every=10_000,
                                 count_kernel=True)
        rng = np.random.default_rng(41)

        def push(n_tenants):
            items = []
            for t in range(n_tenants):
                labels = rng.random(4) < 0.5
                s = rng.standard_normal(4).astype(np.float32)
                items.append((f"t{t}", s, labels))
            fleet.apply_inserts(items)

        push(2)
        baseline = pallas_counts.kernel_cache_sizes()["tenant_local"]
        for n in (3, 5, 8):
            push(n)
        assert pallas_counts.kernel_cache_sizes()[
            "tenant_local"] == baseline, "cache grew inside one bucket"
        push(9)    # crosses T_bucket 8 -> 16
        grown = pallas_counts.kernel_cache_sizes()["tenant_local"]
        assert grown == baseline + 1
        fleet.close()

    def test_flat_cache_keyed_on_buckets_only(self):
        """Two streams of different lengths inside the same bucket
        ladder share every flat-kernel compile."""
        scores, labels = _stream(140, seed=43)
        a = ExactAucIndex(engine="jax", compact_every=32, shards=2,
                          count_kernel=True)
        for i in range(0, 140, 35):
            a.insert_batch(scores[i:i + 35], labels[i:i + 35])
        size_a = pallas_counts.kernel_cache_sizes()["flat_sharded"]
        b = ExactAucIndex(engine="jax", compact_every=32, shards=2,
                          count_kernel=True)
        for i in range(0, 105, 35):
            b.insert_batch(scores[i:i + 35], labels[i:i + 35])
        assert pallas_counts.kernel_cache_sizes()[
            "flat_sharded"] == size_a
        a.close()
        b.close()


class TestKernelRecovery:
    def test_fleet_snapshot_roundtrip_with_kernel(self, tmp_path):
        """Snapshot/restore with count_kernel on — per-tenant wins2
        and streaming estimates bit-identical across the restart."""
        from tuplewise_tpu.serving import MultiTenantEngine, ServingConfig

        cfg = ServingConfig(window=100, compact_every=32,
                            snapshot_dir=str(tmp_path / "d"),
                            snapshot_every=90, count_kernel=True)
        rng = np.random.default_rng(47)
        with MultiTenantEngine(cfg) as eng:
            for i in range(120):
                eng.insert(f"u{i % 3}", rng.standard_normal(2),
                           rng.random(2) < 0.5).result(10.0)
            eng.flush()
            ref = {t: eng.fleet.wins2(t) for t in eng.fleet.tenants()}
        with MultiTenantEngine(cfg, recover=True) as eng2:
            got = {t: eng2.fleet.wins2(t)
                   for t in eng2.fleet.tenants()}
            assert eng2.fleet._ck
        assert ref == got

    def test_sigkill_recover_with_kernel(self, tmp_path):
        """SIGKILL a --count-kernel serve mid-stream, --recover,
        finish — final AUC bit-identical to an uninterrupted
        kernel-off index (one contract covers both engines)."""
        d = str(tmp_path / "rk")
        rng = np.random.default_rng(53)
        events = [(float(rng.standard_normal() + 0.8 * (i % 3 == 0)),
                   int(i % 3 == 0)) for i in range(200)]
        lines = [json.dumps({"op": "insert", "score": s, "label": b})
                 for s, b in events]
        args = [sys.executable, "-m", "tuplewise_tpu.harness.cli",
                "serve", "--policy", "block", "--count-kernel",
                "--mesh-shards", "2", "--snapshot-dir", d,
                "--snapshot-every", "60", "--compact-every", "32"]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        p1 = subprocess.Popen(args, stdin=subprocess.PIPE,
                              stdout=subprocess.PIPE, text=True,
                              env=env, cwd=repo)
        for ln in lines[:120]:
            p1.stdin.write(ln + "\n")
        p1.stdin.flush()
        for _ in range(120):
            assert json.loads(p1.stdout.readline())["ok"]
        os.kill(p1.pid, signal.SIGKILL)
        p1.wait(timeout=30)

        feed = lines[120:] + [json.dumps({"op": "query"})]
        p2 = subprocess.Popen(args + ["--recover"],
                              stdin=subprocess.PIPE,
                              stdout=subprocess.PIPE, text=True,
                              env=env, cwd=repo)
        out, _ = p2.communicate("\n".join(feed) + "\n", timeout=240)
        resp = [json.loads(ln) for ln in out.strip().splitlines()]
        assert all(r["ok"] for r in resp)
        got = [r for r in resp if "auc_exact" in r][-1]["auc_exact"]

        ref = ExactAucIndex(engine="jax", compact_every=32)
        for s, b in events:
            ref.insert_batch([s], [b])
        assert got == ref.auc()
        ref.close()
