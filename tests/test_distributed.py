"""Multi-process launch path [SURVEY §5.8; VERDICT r2 next #8]: the
dcn axis is launchable — two REAL processes coordinate over localhost,
build the (dcn=2, w=2) global mesh from process topology, and the
cross-process hierarchical ring reproduces the single-process oracle."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import os, sys, json
pid, port = int(sys.argv[1]), sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["TUPLEWISE_DIST_COORDINATOR"] = f"localhost:{port}"
os.environ["TUPLEWISE_DIST_NUM_PROCESSES"] = "2"
os.environ["TUPLEWISE_DIST_PROCESS_ID"] = str(pid)
sys.path.insert(0, {repo!r})

from tuplewise_tpu.parallel.distributed import initialize, global_mesh

assert initialize(), "env flags present but initialize() said inactive"

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

jax.config.update("jax_platforms", "cpu")
assert jax.process_count() == 2, jax.process_count()
mesh = global_mesh()
assert mesh.devices.shape == (2, 2), mesh.devices.shape

from tuplewise_tpu.ops.kernels import auc_kernel
from tuplewise_tpu.parallel import ring
from tuplewise_tpu.utils.rng import fold, root_key

m = 64

def body():
    w = lax.axis_index("dcn") * lax.axis_size("w") + lax.axis_index("w")
    k1, k2 = jax.random.split(fold(root_key(0), "shard", w))
    a = jax.random.normal(k1, (m,), jnp.float32) + 1.0
    b = jax.random.normal(k2, (m,), jnp.float32)
    s, c = ring.ring_pair_stats_2d(
        auc_kernel, a, b, ici_axis="w", dcn_axis="dcn",
        tile_a=32, tile_b=32,
    )
    return s / c

val = jax.jit(jax.shard_map(
    body, mesh=mesh, in_specs=(), out_specs=P(), check_vma=False,
))()
print("RESULT", json.dumps({"pid": pid, "value": float(val)}), flush=True)
"""


def _free_port():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow  # spawns 2 fresh jax processes (~20s)
def test_two_process_ring_matches_oracle(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER.replace("{repo!r}", repr(REPO)))
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_", "TUPLEWISE_DIST_"))}
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed smoke test timed out")
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        outs.append(out)

    vals = []
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert line, out
        vals.append(json.loads(line[0][len("RESULT "):])["value"])
    # both processes hold the same psum'd global estimate
    assert vals[0] == pytest.approx(vals[1], abs=1e-7)

    # single-process oracle: regenerate the 4 shard blocks with the
    # same fold chain on the host and take the complete AUC
    import jax
    import jax.numpy as jnp

    from tuplewise_tpu.models.metrics import auc_score
    from tuplewise_tpu.utils.rng import fold, root_key

    a_blocks, b_blocks = [], []
    for w in range(4):
        k1, k2 = jax.random.split(fold(root_key(0), "shard", w))
        a_blocks.append(np.asarray(
            jax.random.normal(k1, (64,), jnp.float32)) + 1.0)
        b_blocks.append(np.asarray(
            jax.random.normal(k2, (64,), jnp.float32)))
    want = auc_score(np.concatenate(a_blocks), np.concatenate(b_blocks))
    assert vals[0] == pytest.approx(want, abs=1e-6)


class TestFlagGating:
    def test_noop_without_flags(self, monkeypatch):
        from tuplewise_tpu.parallel.distributed import initialize

        for k in list(os.environ):
            if k.startswith("TUPLEWISE_DIST_"):
                monkeypatch.delenv(k)
        assert initialize() is False

    @pytest.mark.parametrize("present", [
        "TUPLEWISE_DIST_COORDINATOR", "TUPLEWISE_DIST_PROCESS_ID",
    ])
    def test_partial_flags_raise(self, monkeypatch, present):
        """ANY lone flag is a launch-config error, never a silent
        single-process fallback (a typo'd coordinator var on a pod
        that sets only PROCESS_ID must fail loudly)."""
        from tuplewise_tpu.parallel.distributed import initialize

        for k in ("TUPLEWISE_DIST_COORDINATOR",
                  "TUPLEWISE_DIST_NUM_PROCESSES",
                  "TUPLEWISE_DIST_PROCESS_ID"):
            monkeypatch.delenv(k, raising=False)
        monkeypatch.setenv(
            present, "localhost:1" if "COORD" in present else "0"
        )
        with pytest.raises(ValueError, match="needs coordinator"):
            initialize()

    def test_single_process_mesh_is_local(self):
        from tuplewise_tpu.parallel.distributed import global_mesh

        mesh = global_mesh()   # in-process: 8 virtual CPU devices, 1-D
        assert mesh.devices.size == 8
        assert mesh.axis_names == ("w",)
