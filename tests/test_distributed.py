"""Multi-process launch path [SURVEY §5.8; VERDICT r2 next #8]: the
dcn axis is launchable — two REAL processes coordinate over localhost,
build the (dcn=2, w=2) global mesh from process topology, and the
cross-process hierarchical ring reproduces the single-process oracle."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import os, sys, json
pid, port = int(sys.argv[1]), sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["TUPLEWISE_DIST_COORDINATOR"] = f"localhost:{port}"
os.environ["TUPLEWISE_DIST_NUM_PROCESSES"] = "2"
os.environ["TUPLEWISE_DIST_PROCESS_ID"] = str(pid)
sys.path.insert(0, {repo!r})

from tuplewise_tpu.parallel.distributed import initialize, global_mesh

assert initialize(), "env flags present but initialize() said inactive"

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

jax.config.update("jax_platforms", "cpu")
assert jax.process_count() == 2, jax.process_count()
mesh = global_mesh()
assert mesh.devices.shape == (2, 2), mesh.devices.shape

from tuplewise_tpu.ops.kernels import auc_kernel
from tuplewise_tpu.parallel import ring
from tuplewise_tpu.utils.rng import fold, root_key

m = 64

def body():
    w = lax.axis_index("dcn") * lax.axis_size("w") + lax.axis_index("w")
    k1, k2 = jax.random.split(fold(root_key(0), "shard", w))
    a = jax.random.normal(k1, (m,), jnp.float32) + 1.0
    b = jax.random.normal(k2, (m,), jnp.float32)
    s, c = ring.ring_pair_stats_2d(
        auc_kernel, a, b, ici_axis="w", dcn_axis="dcn",
        tile_a=32, tile_b=32,
    )
    return s / c

val = jax.jit(jax.shard_map(
    body, mesh=mesh, in_specs=(), out_specs=P(), check_vma=False,
))()

# --- mesh-MC loop across the process boundary [VERDICT r3 next #5] ---
# repartitioned scheme: every rep's all-to-all regather crosses the
# dcn (process) axis; estimates must match the single-process oracle
# mesh bit-for-bit (same folds, same mesh shape and axis names).
import numpy as np
from tuplewise_tpu.harness.mesh_mc import make_mesh_mc_runner
from tuplewise_tpu.harness.variance import VarianceConfig

mc_cfg = VarianceConfig(
    backend="mesh", scheme="repartitioned", n_pos=96, n_neg=96,
    n_workers=4, n_rounds=2, n_reps=6,
)
runner = make_mesh_mc_runner(mc_cfg, mesh=mesh, tile=32)
mc = [float(v) for v in np.asarray(runner(np.arange(6)))]

# --- mesh trainer across the process boundary ------------------------
# pmean'd grads + the repartition regather run on the (dcn, w) mesh;
# the final parameters must match the single-process oracle trainer.
from tuplewise_tpu.data import make_gaussians
from tuplewise_tpu.models.pairwise_sgd import TrainConfig, train_pairwise
from tuplewise_tpu.models.scorers import LinearScorer

Xp, Xn = make_gaussians(128, 128, dim=4, separation=1.0, seed=3)
scorer = LinearScorer(dim=4)
t_cfg = TrainConfig(kernel="hinge", lr=0.3, steps=12, n_workers=4,
                    repartition_every=4, seed=3, tile=32)
params, hist = train_pairwise(
    scorer, scorer.init(3), Xp, Xn, t_cfg, mesh=mesh,
)
flat = [float(x) for x in np.ravel(np.asarray(params["w"]))] + [
    float(np.asarray(params["b"]))
]

# --- triplet trainer across the process boundary [VERDICT r4 next #8] -
# budgeted degree-3 SGD: on-device triplet draws per worker per step,
# pmean'd embedding grads and the repartition regather all cross dcn.
from tuplewise_tpu.models.triplet_sgd import (
    TripletTrainConfig, init_embed, train_triplet,
)

tt_cfg = TripletTrainConfig(lr=0.05, steps=8, n_workers=4,
                            repartition_every=4,
                            triplets_per_worker=32, embed_dim=2, seed=5)
tp, th = train_triplet(init_embed(4, 2, seed=5), Xp, Xn, tt_cfg,
                       mesh=mesh)
tflat = [float(x) for x in np.ravel(np.asarray(tp["W"]))]

# --- designed incomplete across the process boundary ------------------
# the device-drawn distinct tuple set (ops.device_design) shards
# [N, per] over the (dcn, w) mesh; each worker's row regather crosses
# the process boundary.
from tuplewise_tpu.estimators.estimator import Estimator

est_d = Estimator("auc", backend="mesh", mesh=mesh, tile_a=32, tile_b=32)
des = est_d.incomplete(Xp[:, 0], Xn[:, 0], n_pairs=64, seed=2,
                       design="swor")

print("RESULT", json.dumps({
    "pid": pid, "value": float(val), "mc": mc, "params": flat,
    "last_loss": float(hist["loss"][-1]),
    "tparams": tflat, "t_last_loss": float(th["loss"][-1]),
    "designed": float(des),
}), flush=True)
"""


def _free_port():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow  # spawns 2 fresh jax processes (~20s)
def test_two_process_ring_matches_oracle(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER.replace("{repo!r}", repr(REPO)))
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_", "TUPLEWISE_DIST_"))}
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed smoke test timed out")
        if (p.returncode != 0
                and "Multiprocess computations aren't implemented"
                in err):
            # this jaxlib's CPU collective backend cannot run
            # cross-process programs at all — environmental, not a
            # regression in the ring (the single-process hierarchical
            # ring is covered by test_mesh_2d)
            for q in procs:
                q.kill()
            pytest.skip("jaxlib CPU backend lacks multiprocess support")
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        outs.append(out)

    recs = []
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert line, out
        recs.append(json.loads(line[0][len("RESULT "):]))
    vals = [r["value"] for r in recs]
    # both processes hold the same psum'd global estimate
    assert vals[0] == pytest.approx(vals[1], abs=1e-7)
    # ... and identical MC estimate arrays and trained parameters
    np.testing.assert_allclose(recs[0]["mc"], recs[1]["mc"], atol=1e-7)
    np.testing.assert_allclose(
        recs[0]["params"], recs[1]["params"], atol=1e-6
    )

    # single-process oracle: regenerate the 4 shard blocks with the
    # same fold chain on the host and take the complete AUC
    import jax
    import jax.numpy as jnp

    from tuplewise_tpu.models.metrics import auc_score
    from tuplewise_tpu.utils.rng import fold, root_key

    a_blocks, b_blocks = [], []
    for w in range(4):
        k1, k2 = jax.random.split(fold(root_key(0), "shard", w))
        a_blocks.append(np.asarray(
            jax.random.normal(k1, (64,), jnp.float32)) + 1.0)
        b_blocks.append(np.asarray(
            jax.random.normal(k2, (64,), jnp.float32)))
    want = auc_score(np.concatenate(a_blocks), np.concatenate(b_blocks))
    assert vals[0] == pytest.approx(want, abs=1e-6)

    # single-process oracle for the MC loop and the trainer: the SAME
    # (2, 2) (dcn, w) mesh built from local virtual devices runs the
    # SAME fold chains, so estimates and trajectories must agree to
    # f32 reduction tolerance [VERDICT r3 next #5]
    from tuplewise_tpu.data import make_gaussians
    from tuplewise_tpu.harness.mesh_mc import make_mesh_mc_runner
    from tuplewise_tpu.harness.variance import VarianceConfig
    from tuplewise_tpu.models.pairwise_sgd import (
        TrainConfig, train_pairwise,
    )
    from tuplewise_tpu.models.scorers import LinearScorer

    from tuplewise_tpu.parallel.mesh import make_mesh_2d

    assert jax.device_count() >= 4
    mesh = make_mesh_2d(2, 2)
    mc_cfg = VarianceConfig(
        backend="mesh", scheme="repartitioned", n_pos=96, n_neg=96,
        n_workers=4, n_rounds=2, n_reps=6,
    )
    runner = make_mesh_mc_runner(mc_cfg, mesh=mesh, tile=32)
    want_mc = np.asarray(runner(np.arange(6)))
    np.testing.assert_allclose(recs[0]["mc"], want_mc, atol=1e-6)

    Xp, Xn = make_gaussians(128, 128, dim=4, separation=1.0, seed=3)
    scorer = LinearScorer(dim=4)
    t_cfg = TrainConfig(kernel="hinge", lr=0.3, steps=12, n_workers=4,
                        repartition_every=4, seed=3, tile=32)
    params, _ = train_pairwise(
        scorer, scorer.init(3), Xp, Xn, t_cfg, mesh=mesh,
    )
    want_flat = np.concatenate([
        np.ravel(np.asarray(params["w"])),
        np.ravel(np.asarray(params["b"])),
    ])
    np.testing.assert_allclose(recs[0]["params"], want_flat, atol=1e-5)

    # triplet trainer + designed incomplete across the process boundary
    # [VERDICT r4 next #8]: same (2, 2) local mesh = same fold chains,
    # so the cross-process run must reproduce the oracle exactly (f32)
    np.testing.assert_allclose(
        recs[0]["tparams"], recs[1]["tparams"], atol=1e-6
    )
    assert recs[0]["designed"] == pytest.approx(
        recs[1]["designed"], abs=1e-7
    )
    from tuplewise_tpu.estimators.estimator import Estimator
    from tuplewise_tpu.models.triplet_sgd import (
        TripletTrainConfig, init_embed, train_triplet,
    )

    tt_cfg = TripletTrainConfig(lr=0.05, steps=8, n_workers=4,
                                repartition_every=4,
                                triplets_per_worker=32, embed_dim=2,
                                seed=5)
    tp, _ = train_triplet(init_embed(4, 2, seed=5), Xp, Xn, tt_cfg,
                          mesh=mesh)
    np.testing.assert_allclose(
        recs[0]["tparams"], np.ravel(np.asarray(tp["W"])), atol=1e-5
    )
    est_d = Estimator("auc", backend="mesh", mesh=mesh,
                      tile_a=32, tile_b=32)
    want_des = est_d.incomplete(Xp[:, 0], Xn[:, 0], n_pairs=64, seed=2,
                                design="swor")
    assert recs[0]["designed"] == pytest.approx(want_des, abs=1e-6)


class TestFlagGating:
    def test_noop_without_flags(self, monkeypatch):
        from tuplewise_tpu.parallel.distributed import initialize

        for k in list(os.environ):
            if k.startswith("TUPLEWISE_DIST_"):
                monkeypatch.delenv(k)
        assert initialize() is False

    @pytest.mark.parametrize("present", [
        "TUPLEWISE_DIST_COORDINATOR", "TUPLEWISE_DIST_PROCESS_ID",
    ])
    def test_partial_flags_raise(self, monkeypatch, present):
        """ANY lone flag is a launch-config error, never a silent
        single-process fallback (a typo'd coordinator var on a pod
        that sets only PROCESS_ID must fail loudly)."""
        from tuplewise_tpu.parallel.distributed import initialize

        for k in ("TUPLEWISE_DIST_COORDINATOR",
                  "TUPLEWISE_DIST_NUM_PROCESSES",
                  "TUPLEWISE_DIST_PROCESS_ID"):
            monkeypatch.delenv(k, raising=False)
        monkeypatch.setenv(
            present, "localhost:1" if "COORD" in present else "0"
        )
        with pytest.raises(ValueError, match="needs coordinator"):
            initialize()

    def test_single_process_mesh_is_local(self):
        from tuplewise_tpu.parallel.distributed import global_mesh

        mesh = global_mesh()   # in-process: 8 virtual CPU devices, 1-D
        assert mesh.devices.size == 8
        assert mesh.axis_names == ("w",)
