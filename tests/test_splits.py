"""Held-out evaluation plumbing [SURVEY §3 "Evaluation"; VERDICT r2
next #2]: stratified splits are disjoint/seeded/class-preserving, the
canonical adult.data/adult.test pair is used when present, and
standardization never sees the test side."""

import numpy as np
import pytest

from tuplewise_tpu.data import (
    load_adult_splits,
    make_gaussian_splits,
    standardize_pair,
    stratified_split,
)
from tests.test_loaders import _ADULT_ROW, _write_adult


class TestStratifiedSplit:
    def test_disjoint_and_complete(self):
        rng = np.random.default_rng(0)
        X = rng.standard_normal((100, 3))
        y = (rng.random(100) < 0.3).astype(int)
        (Xtr, ytr), (Xte, yte) = stratified_split(X, y, 0.25, seed=1)
        assert len(Xtr) + len(Xte) == 100
        # every row lands on exactly one side
        allrows = np.concatenate([Xtr, Xte])
        assert np.array_equal(
            np.sort(allrows, axis=0), np.sort(X, axis=0)
        )

    def test_stratified_proportions(self):
        y = np.array([0] * 80 + [1] * 20)
        X = np.arange(100, dtype=float)[:, None]
        (_, ytr), (_, yte) = stratified_split(X, y, 0.25, seed=0)
        assert (yte == 1).sum() == 5      # round(0.25 * 20)
        assert (yte == 0).sum() == 20     # round(0.25 * 80)
        assert (ytr == 1).sum() == 15

    def test_seeded_reproducible(self):
        rng = np.random.default_rng(2)
        X = rng.standard_normal((50, 2))
        y = (rng.random(50) < 0.5).astype(int)
        a = stratified_split(X, y, 0.3, seed=7)
        b = stratified_split(X, y, 0.3, seed=7)
        assert np.array_equal(a[0][0], b[0][0])
        assert np.array_equal(a[1][0], b[1][0])
        c = stratified_split(X, y, 0.3, seed=8)
        assert not np.array_equal(a[1][0], c[1][0])

    def test_tiny_class_keeps_both_sides(self):
        X = np.arange(12, dtype=float)[:, None]
        y = np.array([0] * 10 + [1] * 2)
        (_, ytr), (_, yte) = stratified_split(X, y, 0.25, seed=0)
        assert (ytr == 1).sum() == 1 and (yte == 1).sum() == 1

    def test_singleton_class_raises(self):
        X = np.zeros((3, 1))
        y = np.array([0, 0, 1])
        with pytest.raises(ValueError, match="class"):
            stratified_split(X, y, 0.25, seed=0)

    def test_bad_fraction_raises(self):
        X, y = np.zeros((4, 1)), np.array([0, 0, 1, 1])
        with pytest.raises(ValueError, match="test_fraction"):
            stratified_split(X, y, 1.5, seed=0)


class TestStandardizePair:
    def test_train_stats_only(self):
        rng = np.random.default_rng(3)
        Xtr = rng.standard_normal((200, 4)) * 3.0 + 1.0
        Xte = rng.standard_normal((50, 4)) * 5.0 - 2.0
        Str, Ste = standardize_pair(Xtr, Xte)
        assert np.allclose(Str.mean(0), 0, atol=1e-9)
        assert np.allclose(Str.std(0), 1, atol=1e-9)
        # test side transformed with TRAIN stats, not its own
        mu, sd = Xtr.mean(0), Xtr.std(0) + 1e-12
        assert np.allclose(Ste, (Xte - mu) / sd)


class TestLoadAdultSplits:
    def test_uses_canonical_test_file(self, tmp_path, monkeypatch):
        _write_adult(tmp_path / "adult.data", n=40)
        # adult.test rows carry the trailing-dot label convention
        (tmp_path / "adult.test").write_text("\n".join(
            _ADULT_ROW.format(
                age=25 + i, work="Private", sex="Male", hours=35,
                label=">50K." if i % 2 else "<=50K.",
            ) for i in range(10)
        ) + "\n")
        monkeypatch.setenv("TUPLEWISE_DATA_DIR", str(tmp_path))
        Xtr, ytr, Xte, yte, meta = load_adult_splits(n=30, seed=0)
        assert meta["split"] == "adult.test"
        assert meta["synthetic"] is False
        assert len(Xtr) == 30 and len(Xte) == 10
        assert Xtr.shape[1] == Xte.shape[1]      # canonical alignment
        assert set(yte) == {0, 1}
        # standardization fit on train only
        assert np.allclose(Xtr.mean(0), 0, atol=1e-9)

    def test_surrogate_fallback_splits(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TUPLEWISE_DATA_DIR", str(tmp_path / "none"))
        Xtr, ytr, Xte, yte, meta = load_adult_splits(
            n=400, seed=0, test_fraction=0.25
        )
        assert meta["synthetic"] is True
        assert meta["split"] == "stratified"
        assert len(Xtr) + len(Xte) == 400
        assert abs(len(Xte) / 400 - 0.25) < 0.02
        assert set(ytr) == {0, 1} and set(yte) == {0, 1}

    def test_single_real_file_falls_back_to_stratified(
        self, tmp_path, monkeypatch
    ):
        _write_adult(tmp_path / "adult.data", n=40)
        monkeypatch.setenv("TUPLEWISE_DATA_DIR", str(tmp_path))
        Xtr, ytr, Xte, yte, meta = load_adult_splits(n=40, seed=0)
        assert meta["synthetic"] is False
        assert meta["split"] == "stratified"
        assert len(Xtr) + len(Xte) == 40


class TestGaussianSplits:
    def test_disjoint_fresh_draws(self):
        Xp, Xn, Xp_te, Xn_te = make_gaussian_splits(
            100, 30, dim=4, separation=1.0, seed=0
        )
        assert Xp.shape == (100, 4) and Xp_te.shape == (30, 4)
        assert Xn.shape == (100, 4) and Xn_te.shape == (30, 4)
        # same underlying draw, positionally disjoint
        assert not np.isin(
            Xp_te.ravel(), Xp.ravel()
        ).any()


def test_cli_train_reports_test_auc(tmp_path, monkeypatch, capsys):
    """The train subcommand trains on the train split and reports both
    train and held-out AUC [VERDICT r2 weak #1]."""
    import json

    from tuplewise_tpu.harness.cli import main

    monkeypatch.setenv("TUPLEWISE_DATA_DIR", str(tmp_path / "none"))
    rc = main([
        "train", "--dataset", "gaussians", "--n", "256",
        "--steps", "5", "--kernel", "hinge", "--seed", "0",
    ])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    for key in ("auc_train", "auc_test", "auc_train_before",
                "auc_test_before"):
        assert key in rec and 0.0 <= rec[key] <= 1.0
    assert rec["auc_test"] > rec["auc_test_before"]
