"""NumPy oracle backend: correctness of the four estimator schemes
[SURVEY §1.2, §5.1]. These pin the semantics every other backend must
reproduce."""

import numpy as np
import pytest

from tuplewise_tpu import Estimator
from tuplewise_tpu.data import make_gaussians, true_gaussian_auc
from tuplewise_tpu.models.metrics import auc_score
from tuplewise_tpu.estimators.variance import (
    incomplete_variance,
    two_sample_variance,
)


@pytest.fixture(scope="module")
def scores():
    X, Y = make_gaussians(400, 300, dim=1, separation=1.0, seed=7)
    return X[:, 0], Y[:, 0]


def brute_force_auc(s1, s2):
    total = 0.0
    for a in s1:
        for b in s2:
            total += float(a > b) + 0.5 * float(a == b)
    return total / (len(s1) * len(s2))


class TestComplete:
    def test_matches_brute_force(self, scores):
        s1, s2 = scores
        est = Estimator("auc", backend="numpy", block_size=64)
        np.testing.assert_allclose(
            est.complete(s1[:50], s2[:40]), brute_force_auc(s1[:50], s2[:40])
        )

    def test_matches_rank_auc(self, scores):
        s1, s2 = scores
        est = Estimator("auc", backend="numpy", block_size=128)
        np.testing.assert_allclose(
            est.complete(s1, s2), auc_score(s1, s2), atol=1e-12
        )

    def test_close_to_population_auc(self):
        X, Y = make_gaussians(4000, 4000, separation=1.0, seed=3)
        est = Estimator("auc", backend="numpy")
        auc = est.complete(X[:, 0], Y[:, 0])
        assert abs(auc - true_gaussian_auc(1.0)) < 0.02

    def test_one_sample_scatter_brute_force(self):
        rng = np.random.default_rng(0)
        A = rng.standard_normal((30, 2))
        est = Estimator("scatter", backend="numpy", block_size=7)
        total = 0.0
        n = len(A)
        for i in range(n):
            for j in range(n):
                if i != j:
                    total += 0.5 * np.sum((A[i] - A[j]) ** 2)
        np.testing.assert_allclose(est.complete(A), total / (n * (n - 1)))

    def test_triplet_complete_brute_force(self):
        rng = np.random.default_rng(1)
        X = rng.standard_normal((12, 3))
        Y = rng.standard_normal((9, 3))
        est = Estimator("triplet_indicator", backend="numpy")
        total = 0.0
        for i in range(12):
            for j in range(12):
                if i == j:
                    continue
                for k in range(9):
                    dp = np.sum((X[i] - X[j]) ** 2)
                    dn = np.sum((X[i] - Y[k]) ** 2)
                    total += float(dn > dp)
        np.testing.assert_allclose(
            est.complete(X, Y), total / (12 * 11 * 9)
        )


class TestLocalAverage:
    def test_unbiased_over_partitions(self, scores):
        """E over SWOR partitions of U^loc equals U_n on the same data
        [SURVEY §1.2 item 2]: every pair is equally likely to co-locate,
        so the partition-average of local U's has mean U_n."""
        s1, s2 = scores
        s1, s2 = s1[:200], s2[:200]
        est = Estimator("auc", backend="numpy", n_workers=4)
        u_n = est.complete(s1, s2)
        vals = [est.local_average(s1, s2, seed=m) for m in range(200)]
        se = np.std(vals) / np.sqrt(len(vals))
        assert abs(np.mean(vals) - u_n) < 4 * se + 1e-6

    def test_higher_variance_than_complete(self):
        """Conditionally on the data, complete U is a constant while the
        local average varies with the partition — by the law of total
        variance this is exactly the extra variance the paper charges to
        ignoring cross-worker pairs [SURVEY §1.2]."""
        X, Y = make_gaussians(240, 240, separation=1.0, seed=11)
        s1, s2 = X[:, 0], Y[:, 0]
        est = Estimator("auc", backend="numpy", n_workers=8)
        vals = [est.local_average(s1, s2, seed=m) for m in range(150)]
        assert np.std(vals) > 1e-3  # partition-induced spread is real


class TestRepartitioned:
    def test_variance_decays_like_one_over_T(self):
        """Fixed data, random reshuffles: rounds are i.i.d. conditionally
        on the data, so Var(U_{N,T} | data) = Var(U_{N,1} | data) / T —
        the 1/T decay that repartitions buy [SURVEY §1.2 item 3]."""
        M = 200
        X, Y = make_gaussians(160, 160, separation=1.0, seed=21)
        s1, s2 = X[:, 0], Y[:, 0]
        est = Estimator("auc", backend="numpy", n_workers=8)
        var_by_T = {}
        for T in (1, 8):
            vals = [
                est.repartitioned(s1, s2, n_rounds=T, seed=3000 + m)
                for m in range(M)
            ]
            var_by_T[T] = np.var(vals)
        ratio = var_by_T[1] / var_by_T[8]
        assert 4.0 < ratio < 16.0

    def test_swr_scheme_runs(self, scores):
        s1, s2 = scores
        est = Estimator("auc", backend="numpy", n_workers=4)
        v = est.repartitioned(s1, s2, n_rounds=3, seed=0, scheme="swr")
        assert 0.0 <= v <= 1.0

    def test_one_sample_swr_unbiased(self):
        """Regression: with-replacement blocks can hold the same original
        point twice; pairs of coincident draws must be excluded by
        original index, else E[U^loc] = (1-1/n) U_n for kernels with
        h(x,x)=0 (the scatter kernel)."""
        rng = np.random.default_rng(3)
        A = rng.standard_normal((40, 2))
        est = Estimator("scatter", backend="numpy", n_workers=4)
        u_n = est.complete(A)
        vals = [
            est.local_average(A, seed=m, scheme="swr") for m in range(1500)
        ]
        se = np.std(vals) / np.sqrt(len(vals))
        bias_if_broken = u_n / len(A)  # the (1 - 1/n) shortfall
        assert se < bias_if_broken / 4  # test has power to see the bias
        assert abs(np.mean(vals) - u_n) < 4 * se


class TestIncomplete:
    def test_unbiased(self, scores):
        s1, s2 = scores
        est = Estimator("auc", backend="numpy")
        u_n = est.complete(s1, s2)
        vals = [
            est.incomplete(s1, s2, n_pairs=500, seed=m) for m in range(300)
        ]
        se = np.std(vals) / np.sqrt(len(vals))
        assert abs(np.mean(vals) - u_n) < 4 * se + 1e-6

    def test_variance_matches_formula(self, scores):
        """Var(U~_B) ~ Var(U_n) + (zeta11 - Var(U_n))/B. Conditionally on
        the data, the sampling variance is (1/B)*Var_pairs(h); check the
        conditional part, which dominates at B=200."""
        s1, s2 = scores
        est = Estimator("auc", backend="numpy")
        B = 200
        vals = [
            est.incomplete(s1, s2, n_pairs=B, seed=m) for m in range(600)
        ]
        emp_var = np.var(vals)
        # conditional variance: Var_pairs(h)/B where Var_pairs is over the
        # empirical pair grid
        u_n = est.complete(s1, s2)
        var_u = two_sample_variance("auc", s1, s2)
        pred = incomplete_variance("auc", s1, s2, n_pairs=B) - var_u
        assert abs(emp_var - pred) / pred < 0.25

    def test_one_sample_incomplete(self):
        rng = np.random.default_rng(5)
        A = rng.standard_normal((300, 3))
        est = Estimator("scatter", backend="numpy")
        u = est.complete(A)
        vals = [est.incomplete(A, n_pairs=400, seed=m) for m in range(200)]
        se = np.std(vals) / np.sqrt(len(vals))
        assert abs(np.mean(vals) - u) < 4 * se + 1e-6

    def test_triplet_incomplete_unbiased(self):
        rng = np.random.default_rng(6)
        X = rng.standard_normal((40, 3))
        Y = rng.standard_normal((30, 3))
        est = Estimator("triplet_indicator", backend="numpy")
        u = est.complete(X, Y)
        vals = [est.incomplete(X, Y, n_pairs=300, seed=m) for m in range(200)]
        se = np.std(vals) / np.sqrt(len(vals))
        assert abs(np.mean(vals) - u) < 4 * se + 1e-6


class TestValidation:
    def test_two_sample_requires_B(self):
        with pytest.raises(ValueError, match="two-sample"):
            Estimator("auc").complete(np.zeros(3))

    def test_diff_kernel_rejects_features(self):
        with pytest.raises(ValueError, match="scalar scores"):
            Estimator("auc").complete(np.zeros((3, 2)), np.zeros((3, 2)))
