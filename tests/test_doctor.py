"""tuplewise doctor [ISSUE 7]: post-hoc diagnosis over a run's
artifacts — fault correlation (every injected fault exactly once),
verdict taxonomy, machine-readable verdict line, CLI contract."""

import json
import os

import pytest

from tuplewise_tpu.obs.doctor import (
    correlate_faults, diagnose, load_metrics_rows, top_self_spans,
)

CHAOS = {"faults": [
    {"point": "compactor_build", "on_call": 1, "action": "error"},
    # on_call low enough to fire within the first few batch-loop
    # iterations at test scale (obs_smoke runs the bigger schedule)
    {"point": "batcher", "on_call": 3, "action": "error"},
    {"point": "poison", "at_events": [150, 900], "value": "nan"},
]}


@pytest.fixture(scope="module")
def chaos_run(tmp_path_factory):
    """One chaos-injected replay, artifacts on disk — the obs_smoke
    schedule at test scale."""
    d = str(tmp_path_factory.mktemp("chaos_run"))
    from tuplewise_tpu.obs.tracing import Tracer
    from tuplewise_tpu.serving import ServingConfig
    from tuplewise_tpu.serving.replay import make_stream, replay

    scores, labels = make_stream(3000, pos_frac=0.5, separation=1.0,
                                 seed=0)
    cfg = ServingConfig(policy="block", compact_every=256,
                        bg_compact=True)
    tracer = Tracer(capacity=1 << 16)
    rec = replay(scores, labels, config=cfg, max_inflight=256,
                 chaos=CHAOS, tracer=tracer,
                 metrics_out=os.path.join(d, "metrics.jsonl"),
                 metrics_every_s=0.1,
                 flight_out=os.path.join(d, "flight.jsonl"))
    tracer.export_jsonl(os.path.join(d, "spans.jsonl"))
    return d, rec


@pytest.fixture(scope="module")
def clean_run(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("clean_run"))
    from tuplewise_tpu.serving import ServingConfig
    from tuplewise_tpu.serving.replay import make_stream, replay

    scores, labels = make_stream(1200, seed=1)
    cfg = ServingConfig(policy="block", compact_every=512)
    replay(scores, labels, config=cfg, max_inflight=128,
           metrics_out=os.path.join(d, "metrics.jsonl"),
           metrics_every_s=0.1,
           flight_out=os.path.join(d, "flight.jsonl"))
    return d


class TestChaosDiagnosis:
    def test_every_injected_fault_exactly_once_correlated(self,
                                                          chaos_run):
        d, _ = chaos_run
        rep = diagnose(run_dir=d)
        faults = rep["faults"]
        # the schedule injects 2 faults + 2 poison events -> 4 entries
        assert len(faults) == 4
        by_point = {}
        for f in faults:
            by_point.setdefault(f["point"], []).append(f)
        assert sorted(by_point) == ["batcher", "compactor_build",
                                    "poison"]
        assert len(by_point["poison"]) == 2
        assert {f["at_event"] for f in by_point["poison"]} == {150, 900}
        # every fault resolved, with named recovery evidence
        for f in faults:
            assert f["resolved"], f
        assert by_point["batcher"][0]["resolution"] == "batcher_restart"
        assert by_point["compactor_build"][0]["resolution"] in (
            "compaction_resumed", "compactor_restarted")
        # trace correlation: the compactor fault's trace id resolves to
        # the build span that died
        assert by_point["compactor_build"][0]["trace_span"] == \
            "compactor.build"
        for f in by_point["poison"]:
            assert f["resolution"] == "poison_rejected"

    def test_verdict_recovered_and_machine_line(self, chaos_run):
        d, _ = chaos_run
        rep = diagnose(run_dir=d)
        assert rep["verdict"] == "recovered"
        line = rep["verdict_line"]
        assert line["healthy"] is True
        assert line["doctor_verdict"] == "recovered"
        assert line["faults"] == line["faults_resolved"] == 4
        json.dumps(line)    # machine-parseable by construction

    def test_report_carries_slo_health_spans_counters(self, chaos_run):
        d, _ = chaos_run
        rep = diagnose(run_dir=d)
        assert rep["slo"] is not None and rep["slo"]["healthy"]
        assert rep["health"]["estimate_ci_width"] is not None
        assert rep["top_self_spans"], "span export not digested"
        names = {s["name"] for s in rep["top_self_spans"]}
        assert any(n.startswith("insert.") for n in names)
        assert "recovery_counters" in rep
        assert rep["run"]["events_total"] > 0
        assert rep["run"]["config_digest"]

    def test_explicit_paths_override_dir_probe(self, chaos_run):
        d, _ = chaos_run
        rep = diagnose(metrics_path=os.path.join(d, "metrics.jsonl"),
                       flight_path=os.path.join(d, "flight.jsonl"))
        assert rep["verdict"] == "recovered"
        # no spans given: correlation still works, span name is None
        assert rep["top_self_spans"] == []


class TestCleanDiagnosis:
    def test_clean_run_is_healthy(self, clean_run):
        rep = diagnose(run_dir=clean_run)
        assert rep["verdict"] == "healthy"
        assert rep["faults"] == []
        assert rep["verdict_line"]["healthy"] is True


class TestDegradedPaths:
    def _artifacts(self, tmp_path, flight_events, metrics_rows=None):
        fdump = tmp_path / "flight.jsonl"
        with open(fdump, "w") as f:
            f.write(json.dumps({"format": "tuplewise-flight-v1",
                                "n_events": len(flight_events),
                                "dropped": 0}) + "\n")
            for e in flight_events:
                f.write(json.dumps(e) + "\n")
        if metrics_rows is not None:
            mpath = tmp_path / "metrics.jsonl"
            with open(mpath, "w") as f:
                for r in metrics_rows:
                    f.write(json.dumps(r) + "\n")
        return str(tmp_path)

    def test_unresolved_fault_degrades(self, tmp_path):
        d = self._artifacts(tmp_path, [
            {"kind": "chaos_inject", "seq": 1, "t_wall": 0.0,
             "t_mono": 0.0, "trace_id": 7, "point": "batcher",
             "action": "error", "on_call": 1}])
        rep = diagnose(run_dir=d)
        assert rep["verdict"].startswith("degraded")
        assert "unresolved" in rep["verdict"]
        assert rep["verdict_line"]["healthy"] is False

    def test_heal_exhaustion_degrades(self, tmp_path):
        d = self._artifacts(tmp_path, [
            {"kind": "heal_exhausted", "seq": 1, "t_wall": 0.0,
             "t_mono": 0.0, "trace_id": None, "error": "x"}])
        rep = diagnose(run_dir=d)
        assert "heal_exhausted" in rep["verdict"]

    def test_slo_breach_in_history_degrades(self, tmp_path):
        rows = [{"seq": i + 1, "ts_wall": float(i), "ts_mono": float(i),
                 "platform": "cpu", "config_digest": "d",
                 "metrics": {
                     "requests_insert_total":
                         {"type": "counter", "value": 100 * (i + 1)},
                     "rejected_total":
                         {"type": "counter", "value": 60 * (i + 1)},
                 }} for i in range(12)]
        d = self._artifacts(tmp_path, [], metrics_rows=rows)
        rep = diagnose(run_dir=d)
        assert "slo_breached" in rep["verdict"]
        assert rep["verdict_line"]["slo_breaches"] > 0

    def test_torn_metrics_tail_tolerated(self, tmp_path):
        mpath = tmp_path / "metrics.jsonl"
        row = {"seq": 1, "ts_wall": 0.0, "ts_mono": 0.0,
               "metrics": {}}
        with open(mpath, "w") as f:
            f.write(json.dumps(row) + "\n")
            f.write('{"seq": 2, "ts_wall": 0.1, "truncat')
        assert load_metrics_rows(str(mpath)) == [row]

    def test_no_artifacts_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            diagnose(run_dir=str(tmp_path))


class TestUnits:
    def test_top_self_spans_subtracts_children(self):
        spans = [
            {"trace_id": 1, "span_id": 1, "parent_id": None,
             "name": "root", "t0_s": 0.0, "dur_s": 1.0},
            {"trace_id": 1, "span_id": 2, "parent_id": 1,
             "name": "child", "t0_s": 0.1, "dur_s": 0.7},
        ]
        top = top_self_spans(spans, 5)
        by = {s["name"]: s for s in top}
        assert by["child"]["self_s"] == pytest.approx(0.7)
        assert by["root"]["self_s"] == pytest.approx(0.3)
        assert top[0]["name"] == "child"

    def test_correlate_ignores_unknown_points_gracefully(self):
        evs = [{"kind": "chaos_inject", "seq": 1, "t_wall": 0.0,
                "point": "train_step", "action": "error",
                "trace_id": None},
               {"kind": "heal", "seq": 2, "t_wall": 0.1,
                "trace_id": None, "mesh_width": 2}]
        faults = correlate_faults(evs, [], [])
        assert len(faults) == 1
        assert faults[0]["resolved"] and faults[0]["resolution"] == \
            "healed"


class TestCli:
    def test_doctor_cli_last_line_is_machine_verdict(self, chaos_run,
                                                     tmp_path,
                                                     capsys):
        d, _ = chaos_run
        from tuplewise_tpu.harness.cli import main

        out_path = str(tmp_path / "report.json")
        rc = main(["doctor", "--dir", d, "--out", out_path])
        assert rc == 0
        lines = capsys.readouterr().out.strip().splitlines()
        line = json.loads(lines[-1])
        assert line["doctor_verdict"] == "recovered"
        assert line["healthy"] is True
        with open(out_path) as f:
            full = json.load(f)
        assert full["verdict"] == "recovered"

    def test_doctor_cli_quiet_and_degraded_exit(self, tmp_path,
                                                capsys):
        fdump = tmp_path / "flight.jsonl"
        with open(fdump, "w") as f:
            f.write(json.dumps({"format": "tuplewise-flight-v1",
                                "n_events": 1, "dropped": 0}) + "\n")
            f.write(json.dumps(
                {"kind": "chaos_inject", "seq": 1, "t_wall": 0.0,
                 "point": "batcher", "action": "error",
                 "trace_id": 1}) + "\n")
        from tuplewise_tpu.harness.cli import main

        rc = main(["doctor", "--flight", str(fdump), "--quiet"])
        assert rc == 2
        lines = capsys.readouterr().out.strip().splitlines()
        assert len(lines) == 1    # quiet: only the machine verdict
        assert json.loads(lines[0])["healthy"] is False
