"""tuplewise doctor [ISSUE 7]: post-hoc diagnosis over a run's
artifacts — fault correlation (every injected fault exactly once),
verdict taxonomy, machine-readable verdict line, CLI contract."""

import json
import os

import pytest

from tuplewise_tpu.obs.doctor import (
    correlate_faults, diagnose, load_metrics_rows, top_self_spans,
)

CHAOS = {"faults": [
    {"point": "compactor_build", "on_call": 1, "action": "error"},
    # on_call low enough to fire within the first few batch-loop
    # iterations at test scale (obs_smoke runs the bigger schedule)
    {"point": "batcher", "on_call": 3, "action": "error"},
    {"point": "poison", "at_events": [150, 900], "value": "nan"},
]}


@pytest.fixture(scope="module")
def chaos_run(tmp_path_factory):
    """One chaos-injected replay, artifacts on disk — the obs_smoke
    schedule at test scale."""
    d = str(tmp_path_factory.mktemp("chaos_run"))
    from tuplewise_tpu.obs.tracing import Tracer
    from tuplewise_tpu.serving import ServingConfig
    from tuplewise_tpu.serving.replay import make_stream, replay

    scores, labels = make_stream(3000, pos_frac=0.5, separation=1.0,
                                 seed=0)
    cfg = ServingConfig(policy="block", compact_every=256,
                        bg_compact=True)
    tracer = Tracer(capacity=1 << 16)
    rec = replay(scores, labels, config=cfg, max_inflight=256,
                 chaos=CHAOS, tracer=tracer,
                 metrics_out=os.path.join(d, "metrics.jsonl"),
                 metrics_every_s=0.1,
                 flight_out=os.path.join(d, "flight.jsonl"))
    tracer.export_jsonl(os.path.join(d, "spans.jsonl"))
    return d, rec


@pytest.fixture(scope="module")
def clean_run(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("clean_run"))
    from tuplewise_tpu.serving import ServingConfig
    from tuplewise_tpu.serving.replay import make_stream, replay

    scores, labels = make_stream(1200, seed=1)
    cfg = ServingConfig(policy="block", compact_every=512)
    replay(scores, labels, config=cfg, max_inflight=128,
           metrics_out=os.path.join(d, "metrics.jsonl"),
           metrics_every_s=0.1,
           flight_out=os.path.join(d, "flight.jsonl"))
    return d


class TestChaosDiagnosis:
    def test_every_injected_fault_exactly_once_correlated(self,
                                                          chaos_run):
        d, _ = chaos_run
        rep = diagnose(run_dir=d)
        faults = rep["faults"]
        # the schedule injects 2 faults + 2 poison events -> 4 entries
        assert len(faults) == 4
        by_point = {}
        for f in faults:
            by_point.setdefault(f["point"], []).append(f)
        assert sorted(by_point) == ["batcher", "compactor_build",
                                    "poison"]
        assert len(by_point["poison"]) == 2
        assert {f["at_event"] for f in by_point["poison"]} == {150, 900}
        # every fault resolved, with named recovery evidence
        for f in faults:
            assert f["resolved"], f
        assert by_point["batcher"][0]["resolution"] == "batcher_restart"
        assert by_point["compactor_build"][0]["resolution"] in (
            "compaction_resumed", "compactor_restarted")
        # trace correlation: the compactor fault's trace id resolves to
        # the build span that died
        assert by_point["compactor_build"][0]["trace_span"] == \
            "compactor.build"
        for f in by_point["poison"]:
            assert f["resolution"] == "poison_rejected"

    def test_verdict_recovered_and_machine_line(self, chaos_run):
        d, _ = chaos_run
        rep = diagnose(run_dir=d)
        assert rep["verdict"] == "recovered"
        line = rep["verdict_line"]
        assert line["healthy"] is True
        assert line["doctor_verdict"] == "recovered"
        assert line["faults"] == line["faults_resolved"] == 4
        json.dumps(line)    # machine-parseable by construction

    def test_report_carries_slo_health_spans_counters(self, chaos_run):
        d, _ = chaos_run
        rep = diagnose(run_dir=d)
        assert rep["slo"] is not None and rep["slo"]["healthy"]
        assert rep["health"]["estimate_ci_width"] is not None
        assert rep["top_self_spans"], "span export not digested"
        names = {s["name"] for s in rep["top_self_spans"]}
        assert any(n.startswith("insert.") for n in names)
        assert "recovery_counters" in rep
        assert rep["run"]["events_total"] > 0
        assert rep["run"]["config_digest"]

    def test_explicit_paths_override_dir_probe(self, chaos_run):
        d, _ = chaos_run
        rep = diagnose(metrics_path=os.path.join(d, "metrics.jsonl"),
                       flight_path=os.path.join(d, "flight.jsonl"))
        assert rep["verdict"] == "recovered"
        # no spans given: correlation still works, span name is None
        assert rep["top_self_spans"] == []


class TestCleanDiagnosis:
    def test_clean_run_is_healthy(self, clean_run):
        rep = diagnose(run_dir=clean_run)
        assert rep["verdict"] == "healthy"
        assert rep["faults"] == []
        assert rep["verdict_line"]["healthy"] is True


class TestDegradedPaths:
    def _artifacts(self, tmp_path, flight_events, metrics_rows=None):
        fdump = tmp_path / "flight.jsonl"
        with open(fdump, "w") as f:
            f.write(json.dumps({"format": "tuplewise-flight-v1",
                                "n_events": len(flight_events),
                                "dropped": 0}) + "\n")
            for e in flight_events:
                f.write(json.dumps(e) + "\n")
        if metrics_rows is not None:
            mpath = tmp_path / "metrics.jsonl"
            with open(mpath, "w") as f:
                for r in metrics_rows:
                    f.write(json.dumps(r) + "\n")
        return str(tmp_path)

    def test_unresolved_fault_degrades(self, tmp_path):
        d = self._artifacts(tmp_path, [
            {"kind": "chaos_inject", "seq": 1, "t_wall": 0.0,
             "t_mono": 0.0, "trace_id": 7, "point": "batcher",
             "action": "error", "on_call": 1}])
        rep = diagnose(run_dir=d)
        assert rep["verdict"].startswith("degraded")
        assert "unresolved" in rep["verdict"]
        assert rep["verdict_line"]["healthy"] is False

    def test_heal_exhaustion_degrades(self, tmp_path):
        d = self._artifacts(tmp_path, [
            {"kind": "heal_exhausted", "seq": 1, "t_wall": 0.0,
             "t_mono": 0.0, "trace_id": None, "error": "x"}])
        rep = diagnose(run_dir=d)
        assert "heal_exhausted" in rep["verdict"]

    def test_slo_breach_in_history_degrades(self, tmp_path):
        rows = [{"seq": i + 1, "ts_wall": float(i), "ts_mono": float(i),
                 "platform": "cpu", "config_digest": "d",
                 "metrics": {
                     "requests_insert_total":
                         {"type": "counter", "value": 100 * (i + 1)},
                     "rejected_total":
                         {"type": "counter", "value": 60 * (i + 1)},
                 }} for i in range(12)]
        d = self._artifacts(tmp_path, [], metrics_rows=rows)
        rep = diagnose(run_dir=d)
        assert "slo_breached" in rep["verdict"]
        assert rep["verdict_line"]["slo_breaches"] > 0

    def test_torn_metrics_tail_tolerated(self, tmp_path):
        mpath = tmp_path / "metrics.jsonl"
        row = {"seq": 1, "ts_wall": 0.0, "ts_mono": 0.0,
               "metrics": {}}
        with open(mpath, "w") as f:
            f.write(json.dumps(row) + "\n")
            f.write('{"seq": 2, "ts_wall": 0.1, "truncat')
        assert load_metrics_rows(str(mpath)) == [row]

    def test_no_artifacts_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            diagnose(run_dir=str(tmp_path))


def _ht_metrics(compile_events=0, batches=100, gc_p99_s=0.0,
                gc_pauses=0, insert_p99_s=0.010, fallbacks=0,
                full_replaces=0):
    """A final-snapshot metrics dict with a self-consistent host-tax
    ledger (bucket sums tile insert_latency_s.sum exactly)."""
    measured = 2.0
    buckets = {"queue_wait": 0.5, "lock_wait": 0.1,
               "host_python": 1.0, "dispatch": 0.2,
               "device_compute": 0.1, "xla_compile": 0.05,
               "gc_pause": 0.05}
    m = {
        "host_tax_waves_total": {"type": "counter", "value": 50},
        "batches_total": {"type": "counter", "value": batches},
        "xla_compile_events_total": {"type": "counter",
                                     "value": compile_events},
        "gc_pauses_total": {"type": "counter", "value": gc_pauses},
        "gc_pause_s": {"type": "histogram", "count": gc_pauses,
                       "sum": gc_p99_s * gc_pauses, "p99": gc_p99_s},
        "tail_exemplars_total": {"type": "counter", "value": 0},
        "insert_latency_s": {"type": "histogram", "count": 100,
                             "sum": measured, "p99": insert_p99_s},
        "host_tax_host_fraction": {"type": "gauge", "value": 0.85},
        "host_tax_device_fraction": {"type": "gauge", "value": 0.10},
        "count_kernel_calls_total": {"type": "counter", "value": 10},
        "count_kernel_fallbacks_total": {"type": "counter",
                                         "value": fallbacks},
        "pack_replaces_total": {"type": "counter", "value": 0},
        "pack_full_replaces_total": {"type": "counter",
                                     "value": full_replaces},
    }
    for b, s in buckets.items():
        m[f"host_tax_{b}_s"] = {"type": "histogram", "count": 100,
                                "sum": s, "p99": s / 100}
    return m


def _rows(metrics, n=3):
    return [{"seq": i + 1, "ts_wall": float(i), "ts_mono": float(i),
             "platform": "cpu", "config_digest": "d",
             "metrics": metrics} for i in range(n)]


class TestHostTaxVerdicts:
    """[ISSUE 14] compile-churn / GC-in-p99 / kernel-fallback
    degraded-reasons and the host_tax report block."""

    def _diagnose(self, tmp_path, metrics):
        mpath = tmp_path / "metrics.jsonl"
        with open(mpath, "w") as f:
            for r in _rows(metrics):
                f.write(json.dumps(r) + "\n")
        return diagnose(metrics_path=str(mpath))

    def test_healthy_run_carries_host_tax_block(self, tmp_path):
        rep = self._diagnose(tmp_path, _ht_metrics())
        assert rep["verdict"] == "healthy"
        ht = rep["host_tax"]
        assert ht["coverage"] == pytest.approx(1.0)
        assert ht["host_fraction"] == 0.85
        assert ht["compile_churn"] is False
        assert ht["gc_in_p99"] is False

    def test_compile_churn_degrades(self, tmp_path):
        # > 1 compile per batch in steady state: 200 events / 100
        # batches = 2000 per 1k
        rep = self._diagnose(tmp_path,
                             _ht_metrics(compile_events=200))
        assert "compile_on_request_thread" in rep["verdict"]
        assert rep["host_tax"]["compile_churn"] is True
        assert rep["verdict_line"]["healthy"] is False

    def test_gc_in_p99_degrades(self, tmp_path):
        # 8ms GC p99 against a 10ms insert p99, 40 pauses
        rep = self._diagnose(tmp_path, _ht_metrics(
            gc_p99_s=0.008, gc_pauses=40, insert_p99_s=0.010))
        assert "gc_in_p99" in rep["verdict"]
        assert rep["host_tax"]["gc_in_p99"] is True

    def test_rare_gc_does_not_degrade(self, tmp_path):
        # a big pause but below GC_MIN_PAUSES occurrences: noise
        rep = self._diagnose(tmp_path, _ht_metrics(
            gc_p99_s=0.008, gc_pauses=3, insert_p99_s=0.010))
        assert rep["verdict"] == "healthy"

    def test_kernel_fallback_degrades(self, tmp_path):
        rep = self._diagnose(tmp_path, _ht_metrics(fallbacks=2,
                                                   full_replaces=5))
        assert "count_kernel_fallback" in rep["verdict"]
        assert rep["kernel"]["count_kernel_fallbacks"] == 2
        assert rep["kernel"]["pack_full_replaces"] == 5

    def test_pre_ledger_artifacts_omit_block(self, tmp_path):
        m = {"insert_latency_s": {"type": "histogram", "count": 10,
                                  "sum": 1.0, "p99": 0.01}}
        rep = self._diagnose(tmp_path, m)
        assert "host_tax" not in rep
        assert rep["verdict"] == "healthy"

    def test_context_overrides_thresholds(self, tmp_path):
        mpath = tmp_path / "metrics.jsonl"
        with open(mpath, "w") as f:
            for r in _rows(_ht_metrics(compile_events=50)):
                f.write(json.dumps(r) + "\n")
        rep = diagnose(metrics_path=str(mpath),
                       context={"compile_churn_per_1k_batches": 100.0})
        assert "compile_on_request_thread" in rep["verdict"]

    def test_delay_fault_resolves_as_latency_absorbed(self, tmp_path):
        evs = [{"kind": "chaos_inject", "seq": 1, "t_wall": 0.0,
                "point": "batcher", "action": "delay", "trace_id": 3},
               {"kind": "tail_exemplar", "seq": 2, "t_wall": 0.1,
                "trace_id": 4, "lat_ms": 80.0, "buckets": {}}]
        faults = correlate_faults(evs, [], [])
        assert len(faults) == 1
        f = faults[0]
        assert f["resolved"] and f["resolution"] == "latency_absorbed"
        assert f["evidence"] == {"tail_exemplars": 1}


class TestUnits:
    def test_top_self_spans_subtracts_children(self):
        spans = [
            {"trace_id": 1, "span_id": 1, "parent_id": None,
             "name": "root", "t0_s": 0.0, "dur_s": 1.0},
            {"trace_id": 1, "span_id": 2, "parent_id": 1,
             "name": "child", "t0_s": 0.1, "dur_s": 0.7},
        ]
        top = top_self_spans(spans, 5)
        by = {s["name"]: s for s in top}
        assert by["child"]["self_s"] == pytest.approx(0.7)
        assert by["root"]["self_s"] == pytest.approx(0.3)
        assert top[0]["name"] == "child"

    def test_correlate_ignores_unknown_points_gracefully(self):
        evs = [{"kind": "chaos_inject", "seq": 1, "t_wall": 0.0,
                "point": "train_step", "action": "error",
                "trace_id": None},
               {"kind": "heal", "seq": 2, "t_wall": 0.1,
                "trace_id": None, "mesh_width": 2}]
        faults = correlate_faults(evs, [], [])
        assert len(faults) == 1
        assert faults[0]["resolved"] and faults[0]["resolution"] == \
            "healed"


class TestCli:
    def test_doctor_cli_last_line_is_machine_verdict(self, chaos_run,
                                                     tmp_path,
                                                     capsys):
        d, _ = chaos_run
        from tuplewise_tpu.harness.cli import main

        out_path = str(tmp_path / "report.json")
        rc = main(["doctor", "--dir", d, "--out", out_path])
        assert rc == 0
        lines = capsys.readouterr().out.strip().splitlines()
        line = json.loads(lines[-1])
        assert line["doctor_verdict"] == "recovered"
        assert line["healthy"] is True
        with open(out_path) as f:
            full = json.load(f)
        assert full["verdict"] == "recovered"

    def test_doctor_cli_quiet_and_degraded_exit(self, tmp_path,
                                                capsys):
        fdump = tmp_path / "flight.jsonl"
        with open(fdump, "w") as f:
            f.write(json.dumps({"format": "tuplewise-flight-v1",
                                "n_events": 1, "dropped": 0}) + "\n")
            f.write(json.dumps(
                {"kind": "chaos_inject", "seq": 1, "t_wall": 0.0,
                 "point": "batcher", "action": "error",
                 "trace_id": 1}) + "\n")
        from tuplewise_tpu.harness.cli import main

        rc = main(["doctor", "--flight", str(fdump), "--quiet"])
        assert rc == 2
        lines = capsys.readouterr().out.strip().splitlines()
        assert len(lines) == 1    # quiet: only the machine verdict
        assert json.loads(lines[0])["healthy"] is False
