"""On-device tuple designs (ops.device_design) [VERDICT r3 next #6]:
the learning-side mirror of the host samplers — distinctness, realized
budgets, and the EXACT conditional-variance closed forms."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tuplewise_tpu.ops.device_design import draw_pair_design_device


class TestDrawPairDesignDevice:
    def test_swor_distinct_exact_budget(self):
        i, j, w = jax.jit(
            lambda k: draw_pair_design_device(k, 37, 53, 800, "swor")
        )(jax.random.PRNGKey(0))
        iw = np.asarray(i)[np.asarray(w) > 0]
        jw = np.asarray(j)[np.asarray(w) > 0]
        assert float(jnp.sum(w)) == 800
        assert len(set(zip(iw.tolist(), jw.tolist()))) == 800
        assert iw.min() >= 0 and iw.max() < 37
        assert jw.min() >= 0 and jw.max() < 53

    def test_bernoulli_realized_size_binomial(self):
        f = jax.jit(
            lambda k: draw_pair_design_device(k, 100, 100, 2000,
                                              "bernoulli")[2]
        )
        sizes = np.asarray([float(jnp.sum(f(jax.random.PRNGKey(s))))
                            for s in range(120)])
        # K ~ Binomial(1e4, 0.2): mean 2000, sd 40
        assert abs(sizes.mean() - 2000) < 4 * 40 / np.sqrt(120)
        assert 25 < sizes.std() < 55

    def test_bernoulli_small_grid_exact_binomial_pmf(self):
        """At G = 16 the realized size is drawn from the EXACT
        Binomial(16, B/G) — histogram over design redraws matches the
        pmf atom by atom, INCLUDING k = 0 (the empty design a true
        Bernoulli can realize) [VERDICT r4 next #2]."""
        from math import comb

        G, B = 16, 4           # n1 = n2 = 4, p = 1/4
        p = B / G
        M = 20000
        f = jax.jit(jax.vmap(
            lambda k: jnp.sum(draw_pair_design_device(
                k, 4, 4, B, "bernoulli")[2])
        ))
        sizes = np.asarray(f(
            jax.vmap(jax.random.PRNGKey)(jnp.arange(M))
        )).astype(int)
        pmf = np.array([
            comb(G, k) * p**k * (1 - p) ** (G - k) for k in range(G + 1)
        ])
        counts = np.bincount(sizes, minlength=G + 1)
        # z-test each atom with expected count >= 5; lump the rest into
        # a tail atom so the whole distribution is covered
        big = pmf * M >= 5
        for k in np.where(big)[0]:
            se = np.sqrt(M * pmf[k] * (1 - pmf[k]))
            assert abs(counts[k] - M * pmf[k]) < 4.5 * se, (
                f"atom {k}: {counts[k]} vs {M * pmf[k]:.1f}"
            )
        q_tail = pmf[~big].sum()
        se_t = np.sqrt(M * q_tail * (1 - q_tail))
        assert abs(counts[~big].sum() - M * q_tail) < 4.5 * se_t
        # the empty design occurs at its true rate (~1.0% here), and
        # the consumer contract prices it as a zero-weight step
        assert counts[0] > 0

    def test_bernoulli_empty_realization_is_zero_weight_step(self):
        """A zero-size bernoulli draw must flow through the consumer
        formula sum(vals*w)/max(sum(w),1) as 0 — and a trainer using
        the design at a tiny per-worker grid stays finite."""
        from tuplewise_tpu.data import make_gaussians
        from tuplewise_tpu.models.pairwise_sgd import (
            TrainConfig, train_pairwise,
        )
        from tuplewise_tpu.models.scorers import LinearScorer

        # direct: find an empty draw and push it through the formula
        f = jax.jit(lambda k: draw_pair_design_device(
            k, 4, 4, 4, "bernoulli"))
        empty = None
        for s in range(500):
            i, j, w = f(jax.random.PRNGKey(s))
            if float(jnp.sum(w)) == 0:
                empty = (i, j, w)
                break
        assert empty is not None, "no empty draw in 500 keys (p~1%/key)"
        i, j, w = empty
        vals = jnp.ones(i.shape[0], jnp.float32)
        loss = jnp.sum(vals * w) / jnp.maximum(jnp.sum(w), 1.0)
        assert float(loss) == 0.0
        # end-to-end: 8 workers x m=4 blocks, B=4 bernoulli — empty
        # draws occur ~1%/worker/step; the run must stay finite
        Xp, Xn = make_gaussians(32, 32, dim=3, separation=1.0, seed=0)
        scorer = LinearScorer(dim=3)
        cfg = TrainConfig(kernel="hinge", lr=0.2, steps=50, n_workers=8,
                          repartition_every=10, pairs_per_worker=4,
                          pair_design="bernoulli", tile=128)
        params, hist = train_pairwise(scorer, scorer.init(0), Xp, Xn,
                                      cfg)
        assert np.isfinite(params["w"]).all()
        assert np.isfinite(hist["loss"]).all()

    def test_one_sample_off_diagonal_distinct(self):
        i, j, w = jax.jit(
            lambda k: draw_pair_design_device(
                k, 40, 39, 500, "swor", one_sample=True)
        )(jax.random.PRNGKey(2))
        iw = np.asarray(i)[np.asarray(w) > 0]
        jw = np.asarray(j)[np.asarray(w) > 0]
        assert not np.any(iw == jw)
        assert len(set(zip(iw.tolist(), jw.tolist()))) == 500

    def test_swr_matches_legacy_sampler(self):
        """pair_design='swr' must reproduce sample_pair_indices draws
        bit-for-bit — seed stability of every committed trainer row."""
        from tuplewise_tpu.ops.pair_tiles import sample_pair_indices

        k = jax.random.PRNGKey(7)
        i0, j0 = sample_pair_indices(k, 64, 48, 256, False)
        i1, j1, w = draw_pair_design_device(k, 64, 48, 256, "swr")
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(j0), np.asarray(j1))
        assert float(jnp.sum(w)) == 256

    def test_rejects_unknown_and_oversized(self):
        with pytest.raises(ValueError, match="unknown sampling design"):
            draw_pair_design_device(jax.random.PRNGKey(0), 8, 8, 4, "x")
        with pytest.raises(ValueError, match="distinct"):
            draw_pair_design_device(jax.random.PRNGKey(0), 8, 8, 65,
                                    "swor")

    def test_triplet_swor_distinct_off_diagonal(self):
        from tuplewise_tpu.ops.device_design import (
            draw_triplet_design_device,
        )

        i, j, k, w = jax.jit(
            lambda kk: draw_triplet_design_device(kk, 20, 15, 900,
                                                  "swor")
        )(jax.random.PRNGKey(3))
        m = np.asarray(w) > 0
        iw, jw, kw = (np.asarray(x)[m] for x in (i, j, k))
        assert float(jnp.sum(w)) == 900
        assert not np.any(iw == jw)
        assert len(set(zip(iw.tolist(), jw.tolist(), kw.tolist()))) == 900
        assert kw.max() < 15 and iw.max() < 20 and jw.max() < 20

    def test_triplet_swr_matches_legacy_trainer_draws(self):
        """triplet_design='swr' reproduces the trainer's historical
        split/randint sequence bit-for-bit — seed stability of the
        committed learning_triplet rows."""
        from tuplewise_tpu.ops.device_design import (
            draw_triplet_design_device,
        )

        key = jax.random.PRNGKey(11)
        ki, kj, kn = jax.random.split(key, 3)
        i0 = jax.random.randint(ki, (64,), 0, 32)
        j0 = jax.random.randint(kj, (64,), 0, 31)
        j0 = jnp.where(j0 >= i0, j0 + 1, j0)
        n0 = jax.random.randint(kn, (64,), 0, 48)
        i1, j1, k1, w = draw_triplet_design_device(key, 32, 48, 64,
                                                   "swr")
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(j0), np.asarray(j1))
        np.testing.assert_array_equal(np.asarray(n0), np.asarray(k1))

    @pytest.mark.parametrize("design", ["swr", "swor", "bernoulli"])
    def test_triplet_conditional_variance_matches_exact_form(
            self, design):
        """Fixed data, indicator kernel: the triplet estimator's
        variance over design redraws matches the fpc form with
        s^2 = U(1-U) and G = n1(n1-1)n2 — the degree-3 version of the
        pair-design audit."""
        from tuplewise_tpu.estimators.variance import (
            conditional_incomplete_variance,
        )
        from tuplewise_tpu.ops.device_design import (
            draw_triplet_design_device,
        )
        from tuplewise_tpu.ops.kernels import get_kernel
        from tuplewise_tpu.ops.pair_tiles import triplet_stats

        k = get_kernel("triplet_indicator")
        rng = np.random.default_rng(2)
        X = jnp.asarray(rng.normal(size=(24, 3)).astype(np.float32))
        Y = jnp.asarray(rng.normal(size=(20, 3)).astype(np.float32)
                        + 0.4)
        s, c = triplet_stats(k, X, Y, tile=8)
        u = float(s) / float(c)
        G, B = 24 * 23 * 20, 5_000

        @jax.jit
        def est(kk):
            i, j, n, w = draw_triplet_design_device(kk, 24, 20, B,
                                                    design)
            vals = k.triplet_values(X[i], X[j], Y[n], jnp)
            return jnp.sum(vals * w) / jnp.sum(w)

        vals = np.asarray([
            float(est(jax.random.PRNGKey(5000 + t))) for t in range(600)
        ])
        pred = conditional_incomplete_variance(
            u * (1 - u), G, n_pairs=B, design=design
        )
        assert abs(vals.var(ddof=1) - pred) / pred < 0.25
        assert abs(vals.mean() - u) < 5 * np.sqrt(pred / 600)

    @pytest.mark.parametrize("design", ["swr", "swor", "bernoulli"])
    def test_conditional_variance_matches_exact_form(self, design):
        """On FIXED scores, the weighted-mean estimator's variance over
        design redraws must match conditional_incomplete_variance
        (s^2 = U(1-U), exact — no plug-in). At B = G/2 swor halves the
        swr value: the finite-population reduction as a measured fact,
        now on the LEARNING side's sampler."""
        from tuplewise_tpu.estimators.variance import (
            conditional_incomplete_variance,
        )
        from tuplewise_tpu.models.metrics import auc_score

        rng = np.random.default_rng(1)
        s1 = jnp.asarray(rng.normal(size=100).astype(np.float32)) + 1.0
        s2 = jnp.asarray(rng.normal(size=100).astype(np.float32))
        u = auc_score(np.asarray(s1), np.asarray(s2))
        G, B = 100 * 100, 5_000

        @jax.jit
        def est(k):
            i, j, w = draw_pair_design_device(k, 100, 100, B, design)
            vals = (s1[i] > s2[j]).astype(jnp.float32)
            return jnp.sum(vals * w) / jnp.sum(w)

        vals = np.asarray([
            float(est(jax.random.PRNGKey(1000 + t))) for t in range(800)
        ])
        pred = conditional_incomplete_variance(
            u * (1 - u), G, n_pairs=B, design=design
        )
        # SE(var)/var ~ sqrt(2/800) = 5%; 4-sigma bound
        assert abs(vals.var(ddof=1) - pred) / pred < 0.2
        assert abs(vals.mean() - u) < 5 * np.sqrt(pred / 800)
