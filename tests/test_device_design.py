"""On-device tuple designs (ops.device_design) [VERDICT r3 next #6]:
the learning-side mirror of the host samplers — distinctness, realized
budgets, and the EXACT conditional-variance closed forms."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tuplewise_tpu.ops.device_design import draw_pair_design_device


class TestDrawPairDesignDevice:
    def test_swor_distinct_exact_budget(self):
        i, j, w = jax.jit(
            lambda k: draw_pair_design_device(k, 37, 53, 800, "swor")
        )(jax.random.PRNGKey(0))
        iw = np.asarray(i)[np.asarray(w) > 0]
        jw = np.asarray(j)[np.asarray(w) > 0]
        assert float(jnp.sum(w)) == 800
        assert len(set(zip(iw.tolist(), jw.tolist()))) == 800
        assert iw.min() >= 0 and iw.max() < 37
        assert jw.min() >= 0 and jw.max() < 53

    def test_bernoulli_realized_size_binomial(self):
        f = jax.jit(
            lambda k: draw_pair_design_device(k, 100, 100, 2000,
                                              "bernoulli")[2]
        )
        sizes = np.asarray([float(jnp.sum(f(jax.random.PRNGKey(s))))
                            for s in range(120)])
        # K ~ Binomial(1e4, 0.2): mean 2000, sd 40
        assert abs(sizes.mean() - 2000) < 4 * 40 / np.sqrt(120)
        assert 25 < sizes.std() < 55

    def test_one_sample_off_diagonal_distinct(self):
        i, j, w = jax.jit(
            lambda k: draw_pair_design_device(
                k, 40, 39, 500, "swor", one_sample=True)
        )(jax.random.PRNGKey(2))
        iw = np.asarray(i)[np.asarray(w) > 0]
        jw = np.asarray(j)[np.asarray(w) > 0]
        assert not np.any(iw == jw)
        assert len(set(zip(iw.tolist(), jw.tolist()))) == 500

    def test_swr_matches_legacy_sampler(self):
        """pair_design='swr' must reproduce sample_pair_indices draws
        bit-for-bit — seed stability of every committed trainer row."""
        from tuplewise_tpu.ops.pair_tiles import sample_pair_indices

        k = jax.random.PRNGKey(7)
        i0, j0 = sample_pair_indices(k, 64, 48, 256, False)
        i1, j1, w = draw_pair_design_device(k, 64, 48, 256, "swr")
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(j0), np.asarray(j1))
        assert float(jnp.sum(w)) == 256

    def test_rejects_unknown_and_oversized(self):
        with pytest.raises(ValueError, match="unknown sampling design"):
            draw_pair_design_device(jax.random.PRNGKey(0), 8, 8, 4, "x")
        with pytest.raises(ValueError, match="distinct"):
            draw_pair_design_device(jax.random.PRNGKey(0), 8, 8, 65,
                                    "swor")

    def test_triplet_swor_distinct_off_diagonal(self):
        from tuplewise_tpu.ops.device_design import (
            draw_triplet_design_device,
        )

        i, j, k, w = jax.jit(
            lambda kk: draw_triplet_design_device(kk, 20, 15, 900,
                                                  "swor")
        )(jax.random.PRNGKey(3))
        m = np.asarray(w) > 0
        iw, jw, kw = (np.asarray(x)[m] for x in (i, j, k))
        assert float(jnp.sum(w)) == 900
        assert not np.any(iw == jw)
        assert len(set(zip(iw.tolist(), jw.tolist(), kw.tolist()))) == 900
        assert kw.max() < 15 and iw.max() < 20 and jw.max() < 20

    def test_triplet_swr_matches_legacy_trainer_draws(self):
        """triplet_design='swr' reproduces the trainer's historical
        split/randint sequence bit-for-bit — seed stability of the
        committed learning_triplet rows."""
        from tuplewise_tpu.ops.device_design import (
            draw_triplet_design_device,
        )

        key = jax.random.PRNGKey(11)
        ki, kj, kn = jax.random.split(key, 3)
        i0 = jax.random.randint(ki, (64,), 0, 32)
        j0 = jax.random.randint(kj, (64,), 0, 31)
        j0 = jnp.where(j0 >= i0, j0 + 1, j0)
        n0 = jax.random.randint(kn, (64,), 0, 48)
        i1, j1, k1, w = draw_triplet_design_device(key, 32, 48, 64,
                                                   "swr")
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(j0), np.asarray(j1))
        np.testing.assert_array_equal(np.asarray(n0), np.asarray(k1))

    @pytest.mark.parametrize("design", ["swr", "swor", "bernoulli"])
    def test_triplet_conditional_variance_matches_exact_form(
            self, design):
        """Fixed data, indicator kernel: the triplet estimator's
        variance over design redraws matches the fpc form with
        s^2 = U(1-U) and G = n1(n1-1)n2 — the degree-3 version of the
        pair-design audit."""
        from tuplewise_tpu.estimators.variance import (
            conditional_incomplete_variance,
        )
        from tuplewise_tpu.ops.device_design import (
            draw_triplet_design_device,
        )
        from tuplewise_tpu.ops.kernels import get_kernel
        from tuplewise_tpu.ops.pair_tiles import triplet_stats

        k = get_kernel("triplet_indicator")
        rng = np.random.default_rng(2)
        X = jnp.asarray(rng.normal(size=(24, 3)).astype(np.float32))
        Y = jnp.asarray(rng.normal(size=(20, 3)).astype(np.float32)
                        + 0.4)
        s, c = triplet_stats(k, X, Y, tile=8)
        u = float(s) / float(c)
        G, B = 24 * 23 * 20, 5_000

        @jax.jit
        def est(kk):
            i, j, n, w = draw_triplet_design_device(kk, 24, 20, B,
                                                    design)
            vals = k.triplet_values(X[i], X[j], Y[n], jnp)
            return jnp.sum(vals * w) / jnp.sum(w)

        vals = np.asarray([
            float(est(jax.random.PRNGKey(5000 + t))) for t in range(600)
        ])
        pred = conditional_incomplete_variance(
            u * (1 - u), G, n_pairs=B, design=design
        )
        assert abs(vals.var(ddof=1) - pred) / pred < 0.25
        assert abs(vals.mean() - u) < 5 * np.sqrt(pred / 600)

    @pytest.mark.parametrize("design", ["swr", "swor", "bernoulli"])
    def test_conditional_variance_matches_exact_form(self, design):
        """On FIXED scores, the weighted-mean estimator's variance over
        design redraws must match conditional_incomplete_variance
        (s^2 = U(1-U), exact — no plug-in). At B = G/2 swor halves the
        swr value: the finite-population reduction as a measured fact,
        now on the LEARNING side's sampler."""
        from tuplewise_tpu.estimators.variance import (
            conditional_incomplete_variance,
        )
        from tuplewise_tpu.models.metrics import auc_score

        rng = np.random.default_rng(1)
        s1 = jnp.asarray(rng.normal(size=100).astype(np.float32)) + 1.0
        s2 = jnp.asarray(rng.normal(size=100).astype(np.float32))
        u = auc_score(np.asarray(s1), np.asarray(s2))
        G, B = 100 * 100, 5_000

        @jax.jit
        def est(k):
            i, j, w = draw_pair_design_device(k, 100, 100, B, design)
            vals = (s1[i] > s2[j]).astype(jnp.float32)
            return jnp.sum(vals * w) / jnp.sum(w)

        vals = np.asarray([
            float(est(jax.random.PRNGKey(1000 + t))) for t in range(800)
        ])
        pred = conditional_incomplete_variance(
            u * (1 - u), G, n_pairs=B, design=design
        )
        # SE(var)/var ~ sqrt(2/800) = 5%; 4-sigma bound
        assert abs(vals.var(ddof=1) - pred) / pred < 0.2
        assert abs(vals.mean() - u) < 5 * np.sqrt(pred / 800)
