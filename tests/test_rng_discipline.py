"""PRNG key-discipline audit [SURVEY §5.3]."""

import jax
import numpy as np
import pytest

from tuplewise_tpu.utils.rng import audit_keys, fold, root_key


def test_distinct_chains_pass():
    with audit_keys():
        k = root_key(0)
        sub = fold(k, "shard", 0)
        fold(k, "shard", 1)
        fold(k, "mc_rep", 0)     # same index, different purpose: fine
        fold(sub, "shard", 0)    # same chain tail, different parent: fine


def test_duplicate_chain_raises():
    with audit_keys():
        k = root_key(0)
        fold(k, "shard", 3)
        with pytest.raises(AssertionError, match="key-discipline"):
            fold(k, "shard", 3)


def test_no_audit_no_overhead():
    k = root_key(0)
    fold(k, "shard", 3)
    fold(k, "shard", 3)  # outside a scope nothing is recorded


def test_in_jit_folds_are_skipped():
    """Traced indices can't be observed; the audit must not crash jit."""
    with audit_keys():
        @jax.jit
        def f(t):
            return jax.random.uniform(fold(root_key(0), "step", t))

        a, b = float(f(0)), float(f(1))
        assert a != b


def test_estimator_paths_are_clean():
    """The library's own host-side orchestration under audit: distinct
    seeds/purposes everywhere, no reuse."""
    from tuplewise_tpu import Estimator
    from tuplewise_tpu.data import make_gaussians

    X, Y = make_gaussians(400, 400, dim=1, separation=1.0, seed=0)
    s1, s2 = X[:, 0], Y[:, 0]
    with audit_keys():
        est = Estimator("auc", backend="jax", n_workers=4,
                        tile_a=64, tile_b=64)
        est.complete(s1, s2)
        est.local_average(s1, s2, seed=0)
        est.local_average(s1, s2, seed=1)   # distinct seed, distinct root
        est.repartitioned(s1, s2, n_rounds=3, seed=2)
        est.incomplete(s1, s2, n_pairs=500, seed=3)


def test_nested_scopes_share_state():
    with audit_keys():
        k = root_key(5)
        fold(k, "a")
        with audit_keys():
            with pytest.raises(AssertionError):
                fold(k, "a")
