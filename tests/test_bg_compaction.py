"""Background compaction [ISSUE 2 tentpole]: the double-buffered swap
must be invisible to the statistic.

wins2 is updated synchronously on the mutator's thread; compaction —
foreground or background — only moves values between containers. So
ANY interleaving of inserts/evictions with an in-flight background
build must yield prefix AUCs bit-identical to the synchronous index
and the NumPy oracle. The tests drive that property two ways: random
insert schedules racing the live compactor, and a deterministic
interleave that freezes the build mid-flight via the test hook.
"""

import threading

import numpy as np
import pytest

from tuplewise_tpu.models.metrics import auc_score
from tuplewise_tpu.serving import ExactAucIndex, MicroBatchEngine
from tuplewise_tpu.serving.replay import make_stream, replay
from tuplewise_tpu.utils.profiling import MetricsRegistry


def _stream(n, seed=7, pos_frac=0.45):
    scores, labels = make_stream(n, pos_frac=pos_frac, separation=1.0,
                                 seed=seed)
    return scores.astype(np.float32), labels


def _oracle(scores, labels):
    pos, neg = scores[labels], scores[~labels]
    if len(pos) == 0 or len(neg) == 0:
        return None
    return auc_score(pos.astype(np.float64), neg.astype(np.float64))


@pytest.mark.parametrize("window", [None, 257])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_random_schedules_match_sync_index(window, seed):
    """Property: any insert schedule racing the live background
    compactor yields prefix AUCs bit-identical to the synchronous
    index (numpy engine keeps the test fast and jit-free)."""
    rng = np.random.default_rng(seed)
    scores, labels = _stream(2500, seed=seed + 20)
    bg = ExactAucIndex(engine="numpy", compact_every=32, window=window,
                       bg_compact=True)
    sync = ExactAucIndex(engine="numpy", compact_every=32, window=window)
    off = 0
    while off < len(scores):
        k = min(off + int(rng.integers(1, 64)), len(scores))
        bg.insert_batch(scores[off:k], labels[off:k])
        sync.insert_batch(scores[off:k], labels[off:k])
        off = k
        assert bg._wins2 == sync._wins2, off
        assert bg.auc() == sync.auc(), off
    bg.compact()
    assert bg._wins2 == sync._wins2
    tail = slice(-window if window else None, None)
    assert bg.auc() == pytest.approx(
        _oracle(scores[tail], labels[tail]), abs=1e-6)
    assert bg.n_compactions > 0, "schedule never crossed a compaction"
    bg.close()


def test_deterministic_interleave_frozen_build():
    """Freeze a build mid-flight (test hook), keep inserting AND
    evicting against the frozen snapshot, then release: every prefix
    AUC during the frozen window and the post-swap state must equal
    the synchronous index bit-for-bit."""
    scores, labels = _stream(3000, seed=3)
    started, hold = threading.Event(), threading.Event()

    bg = ExactAucIndex(engine="numpy", compact_every=64, window=400,
                       bg_compact=True)
    sync = ExactAucIndex(engine="numpy", compact_every=64, window=400)

    def hook(side):
        started.set()
        assert hold.wait(timeout=30.0)

    bg._bg_test_hook = hook
    off = 0

    def feed(k):
        nonlocal off
        k = min(off + k, len(scores))
        bg.insert_batch(scores[off:k], labels[off:k])
        sync.insert_batch(scores[off:k], labels[off:k])
        off = k
        assert bg._wins2 == sync._wins2, off
        assert bg.auc() == sync.auc(), off

    # drive until a background build is in flight, then race it: the
    # window forces evictions whose tombstones land mid-build
    while not started.is_set() and off < 1000:
        feed(13)
    assert started.is_set(), "no background build was triggered"
    for _ in range(40):
        feed(17)
    assert bg._pos.building or bg._neg.building or True  # raced or done
    hold.set()
    while off < len(scores):
        feed(29)
    bg.compact()
    assert bg._wins2 == sync._wins2
    assert bg.auc() == pytest.approx(
        _oracle(scores[-400:], labels[-400:]), abs=1e-6)
    bg.close()


def test_sharded_plus_bg_compact():
    """The two tentpole halves compose: sharded base runs with a
    background compactor stay bit-identical to the plain index."""
    scores, labels = _stream(1200, seed=17)
    both = ExactAucIndex(engine="jax", compact_every=64, shards=2,
                         bg_compact=True, window=500)
    plain = ExactAucIndex(engine="jax", compact_every=64, window=500)
    for i in range(0, 1200, 41):
        k = min(i + 41, 1200)
        both.insert_batch(scores[i:k], labels[i:k])
        plain.insert_batch(scores[i:k], labels[i:k])
        assert both._wins2 == plain._wins2, k
    both.compact()
    assert both.auc() == plain.auc()
    both.close()


def test_sharded_delta_plus_bg_compact():
    """[ISSUE 5] All three layers compose: delta compaction tiers
    racing the background compactor against a sliding window stay
    bit-identical to the plain index, and a major merge actually
    lands."""
    scores, labels = _stream(2400, seed=23)
    rng = np.random.default_rng(2)
    both = ExactAucIndex(engine="jax", compact_every=48, shards=2,
                         bg_compact=True, window=500,
                         delta_fraction=0.25, max_delta_runs=3)
    plain = ExactAucIndex(engine="jax", compact_every=48, window=500)
    off = 0
    while off < len(scores):
        k = min(off + int(rng.integers(1, 64)), len(scores))
        both.insert_batch(scores[off:k], labels[off:k])
        plain.insert_batch(scores[off:k], labels[off:k])
        off = k
        assert both._wins2 == plain._wins2, off
        assert both.auc() == plain.auc(), off
    both.wait_idle()
    assert both.state()["n_major_merges"] > 0
    assert both.state()["last_compactor_error"] is None
    both.compact()
    assert both._wins2 == plain._wins2
    assert both.auc() == pytest.approx(
        _oracle(scores[-500:], labels[-500:]), abs=1e-6)
    both.close()


def test_compact_drains_inflight_builds():
    scores, labels = _stream(600, seed=5)
    idx = ExactAucIndex(engine="numpy", compact_every=32, bg_compact=True)
    idx.insert_batch(scores, labels)
    before = idx.auc()
    idx.compact()
    assert not idx._pos.buf and not idx._pos.tomb
    assert not idx._neg.buf and not idx._neg.tomb
    assert idx.auc() == before
    idx.close()


def test_pause_histogram_and_counter_recorded():
    m = MetricsRegistry()
    idx = ExactAucIndex(engine="numpy", compact_every=32, bg_compact=True,
                        metrics=m)
    scores, labels = _stream(500, seed=9)
    idx.insert_batch(scores, labels)
    idx.compact()
    snap = m.snapshot()
    assert snap["compactions_total"]["value"] == idx.n_compactions > 0
    assert snap["compaction_pause_s"]["count"] == idx.n_compactions
    assert snap["compaction_pause_s"]["p99"] is not None
    idx.close()


def test_close_is_idempotent():
    idx = ExactAucIndex(engine="numpy", bg_compact=True)
    idx.close()
    idx.close()


class TestEngineAndReplay:
    def test_engine_stats_carry_pause_and_insert_latency(self):
        scores, labels = _stream(700, seed=11)
        with MicroBatchEngine(bg_compact=True, compact_every=64,
                              policy="block", engine="numpy") as eng:
            eng.insert(scores, labels).result(30.0)
            snap = eng.flush()
        assert snap["index"]["bg_compact"] is True
        assert "compaction_pause_s" in snap["metrics"]
        assert "insert_latency_s" in snap["metrics"]
        assert snap["metrics"]["insert_latency_s"]["count"] > 0

    def test_replay_record_has_percentiles_and_parity(self):
        scores, labels = make_stream(1500, seed=2)
        rec = replay(scores, labels, bg_compact=True, compact_every=64,
                     policy="block", engine="numpy", max_inflight=128)
        for f in ("insert_latency_p50_ms", "insert_latency_p95_ms",
                  "insert_latency_p99_ms", "compaction_pause_p99_ms",
                  "compactions"):
            assert rec[f] is not None, f
        assert rec["auc_abs_err"] <= 1e-9
        assert rec["config"]["bg_compact"] is True
