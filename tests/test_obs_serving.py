"""Serving-path observability integration tests [ISSUE 6]: span
integrity under concurrent batcher/compactor/healer activity, stage
attribution, Chrome-trace schema, serve/replay report parity, flight
persistence next to snapshots, and the tracing-disabled guard."""

import io
import json
import sys
import threading

import numpy as np

import pytest

from tuplewise_tpu.obs import FlightRecorder, Tracer
from tuplewise_tpu.serving import MicroBatchEngine, ServingConfig
from tuplewise_tpu.serving.replay import make_stream, replay


def _stream(n, seed=0):
    return make_stream(n, pos_frac=0.5, separation=1.0, seed=seed)


class TestTracedServing:
    def test_span_integrity_under_concurrency(self):
        """Batcher + background compactor + multiple submitter threads
        all record concurrently; every parent id must resolve inside
        the same trace and insert stage spans must tile their root."""
        scores, labels = _stream(3000)
        tracer = Tracer(capacity=1 << 16)
        cfg = ServingConfig(policy="block", compact_every=128,
                            bg_compact=True, flush_timeout_s=0.001)
        with MicroBatchEngine(cfg, tracer=tracer) as eng:
            def submit(lo, hi):
                for i in range(lo, hi):
                    eng.insert(scores[i], labels[i]).result(30.0)

            threads = [threading.Thread(target=submit,
                                        args=(i * 750, (i + 1) * 750))
                       for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            eng.index.wait_idle()
        spans = tracer.spans()
        assert tracer.dropped == 0
        by_id = {s["span_id"]: s for s in spans}
        roots = {}
        for s in spans:
            if s["parent_id"] is None:
                roots.setdefault(s["trace_id"], []).append(s)
            else:
                parent = by_id[s["parent_id"]]      # must resolve
                assert parent["trace_id"] == s["trace_id"]
        # one root per trace — a child never leaks into another trace
        assert all(len(r) == 1 for r in roots.values())
        # compactor activity traced on its own thread, its own traces
        compactor = [s for s in spans
                     if s["thread"] == "tuplewise-compactor"]
        assert any(s["name"] == "compactor.build" for s in compactor)
        insert_threads = {s["thread"] for s in spans
                          if s["name"] == "request.insert"}
        assert len(insert_threads) >= 2     # concurrent submitters

    def test_stage_spans_tile_each_insert(self):
        scores, labels = _stream(1200)
        tracer = Tracer()
        rec = replay(scores, labels,
                     config=ServingConfig(policy="block",
                                          compact_every=256),
                     max_inflight=64, tracer=tracer)
        spans = tracer.spans()
        child_sum = {}
        for s in spans:
            if s["parent_id"] is not None:
                child_sum[s["parent_id"]] = \
                    child_sum.get(s["parent_id"], 0.0) + s["dur_s"]
        roots = [s for s in spans if s["name"] == "request.insert"]
        assert len(roots) == 1200
        for r in roots:
            if r["dur_s"] > 0:
                assert child_sum.get(r["span_id"], 0.0) \
                    >= 0.95 * r["dur_s"]
        # ... and the histogram-side attribution agrees exactly
        assert rec["stage_attribution"]["coverage"] \
            == pytest.approx(1.0, abs=1e-6)

    def test_chrome_export_schema(self, tmp_path):
        scores, labels = _stream(400)
        out = str(tmp_path / "trace.json")
        rec = replay(scores, labels,
                     config=ServingConfig(policy="block"),
                     max_inflight=64, trace_out=out)
        assert rec["trace_out"] == out and rec["trace_spans"] > 0
        doc = json.load(open(out))
        assert isinstance(doc["traceEvents"], list)
        x = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert x, "no complete events"
        for e in x:
            assert {"name", "pid", "tid", "ts", "dur"} <= set(e)
            assert e["dur"] >= 0
            assert "trace_id" in e["args"] and "span_id" in e["args"]
        # thread metadata present for every tid used
        tids = {e["tid"] for e in x}
        named = {e["tid"] for e in doc["traceEvents"]
                 if e.get("ph") == "M" and e["name"] == "thread_name"}
        assert tids <= named

    def test_disabled_tracing_is_default_and_structural_noop(self):
        scores, labels = _stream(300)
        cfg = ServingConfig(policy="block", compact_every=128)
        with MicroBatchEngine(cfg) as eng:
            assert eng.tracer is None
            assert eng.index.tracer is None
            fut = eng.insert(scores, labels)
            assert fut.result(30.0) == 300
            eng.flush()
            stats = eng.stats()
        # stage histograms still attribute latency with tracing off
        m = stats["metrics"]
        assert m["insert_stage_queue_wait_s"]["count"] == 1
        total = m["insert_latency_s"]["sum"]
        attributed = sum(
            m[f"insert_stage_{s}_s"]["sum"]
            for s in ("queue_wait", "coalesce", "wal_append",
                      "index_insert", "stream_extend", "snapshot",
                      "resolve"))
        assert attributed == pytest.approx(total, rel=1e-9)

    @pytest.mark.slow
    def test_trace_disabled_overhead_close_to_traced_off_baseline(self):
        """Coarse overhead guard (the authoritative one is bench.py
        --streaming vs the PR 5 baseline): tracing OFF must not be
        slower than tracing ON — and the two runs bound the plumbing
        cost of this PR's always-on stage attribution."""
        scores, labels = _stream(20_000, seed=3)
        cfg = ServingConfig(policy="block", compact_every=1024,
                            bg_compact=True, flush_timeout_s=0.0005)
        base = replay(scores, labels, config=cfg, warmup=True,
                      max_inflight=64)
        traced = replay(scores, labels, config=cfg, warmup=True,
                        max_inflight=64, tracer=Tracer(capacity=1 << 18))
        assert base["insert_latency_p99_ms"] \
            <= 1.5 * traced["insert_latency_p99_ms"]


class TestReportParity:
    def test_serve_exit_summary_matches_replay_report(self, monkeypatch,
                                                      capsys):
        """ONE report builder feeds both surfaces: the serve exit
        summary and the replay record must carry the same keys and,
        for a deterministic stream, the same counter values."""
        from tuplewise_tpu.harness.cli import _serve_stdin

        scores, labels = _stream(600, seed=1)
        cfg = ServingConfig(policy="block", compact_every=128,
                            bg_compact=False)
        rec = replay(scores, labels, config=cfg, max_inflight=32)
        lines = "".join(
            json.dumps({"op": "insert", "score": float(s),
                        "label": int(l)}) + "\n"
            for s, l in zip(scores, labels))
        monkeypatch.setattr(sys, "stdin", io.StringIO(lines))
        assert _serve_stdin(cfg) == 0
        err = capsys.readouterr().err
        summary = None
        for line in err.splitlines():
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "exit_summary" in row:
                summary = row["exit_summary"]
        assert summary is not None
        rep = rec["report"]
        # serve additionally reports flight-event counts; everything
        # else is the SAME builder output
        assert set(rep) | {"flight_events"} == set(summary)
        for k in ("compactions_total", "rejected_total",
                  "poison_rejects", "deadline_expired_total",
                  "reshard_events", "batcher_restarts",
                  "major_merge_fallbacks", "bytes_h2d"):
            assert summary[k] == rep[k], k

    def test_replay_faults_block_uses_unified_counters(self):
        scores, labels = _stream(800, seed=2)
        chaos = {"faults": [
            {"point": "poison", "at_events": [10, 20], "value": "inf"}]}
        rec = replay(scores, labels,
                     config=ServingConfig(policy="block",
                                          compact_every=256),
                     max_inflight=32, chaos=chaos)
        from tuplewise_tpu.obs.report import recovery_counters

        expected = set(recovery_counters({})) | {"chaos"}
        assert set(rec["faults"]) == expected
        assert rec["faults"]["poison_rejects"] == 2
        assert rec["report"]["poison_rejects"] == 2


class TestFlightInServing:
    def test_flight_dump_lands_next_to_snapshots(self, tmp_path):
        snapdir = str(tmp_path / "snap")
        scores, labels = _stream(900, seed=4)
        cfg = ServingConfig(policy="block", compact_every=128,
                            snapshot_dir=snapdir, snapshot_every=256)
        with MicroBatchEngine(cfg) as eng:
            for i in range(0, 900, 45):
                eng.insert(scores[i:i + 45], labels[i:i + 45])
            eng.flush()
        dump = FlightRecorder.load_dump(
            str(tmp_path / "snap" / "flight.jsonl"))
        kinds = {e["kind"] for e in dump["events"]}
        assert "wal_seal" in kinds
        assert "snapshot_landed" in kinds
        assert "engine_closed" in kinds
        seqs = [e["seq"] for e in dump["events"]]
        assert seqs == sorted(seqs)

    def test_lifecycle_events_recorded(self):
        scores, labels = _stream(600, seed=5)
        cfg = ServingConfig(policy="block", compact_every=128)
        with MicroBatchEngine(cfg) as eng:
            eng.insert(scores, labels).result(30.0)
            with pytest.raises(Exception):
                eng.insert([float("nan")], [1]).result(30.0)
            eng.flush()
            counts = eng.flight.counts()
        assert counts.get("poison_reject") == 1
        assert counts.get("compaction", 0) >= 1

    def test_metrics_flusher_through_replay(self, tmp_path):
        p = str(tmp_path / "metrics.jsonl")
        scores, labels = _stream(500, seed=6)
        rec = replay(scores, labels,
                     config=ServingConfig(policy="block"),
                     max_inflight=64, metrics_out=p,
                     metrics_every_s=0.05)
        assert rec["metrics_out"] == p
        rows = [json.loads(x) for x in open(p)]
        assert len(rows) >= 2
        assert rows[-1]["metrics"]["events_total"]["value"] == 500
        # live gauges are present in the stream
        assert "queue_depth_live" in rows[-1]["metrics"]
        assert "mesh_width" in rows[-1]["metrics"]
