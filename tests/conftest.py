"""Force an 8-device CPU platform BEFORE any jax computation [SURVEY §5.1].

This is how the multi-chip code paths (mesh / psum / ppermute ring) run
in CI with no TPU: XLA exposes 8 virtual CPU devices and the exact same
shard_map code executes on them.

NOTE: this environment PRELOADS jax at interpreter startup with
``jax_platforms='axon,cpu'`` already set via config (the env var is
ignored), so we must override through jax.config — and still set the env
vars first for any subprocesses tests spawn.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (preloaded anyway; see module docstring)

jax.config.update("jax_platforms", "cpu")
assert jax.device_count() == 8, (
    f"expected 8 virtual CPU devices, got {jax.devices()} — "
    "jax was initialized before conftest could force the CPU platform"
)
