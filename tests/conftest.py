"""Force an 8-device CPU platform BEFORE jax initializes [SURVEY §5.1].

This is how the multi-chip code paths (mesh / psum / ppermute ring) run
in CI with no TPU: XLA exposes 8 virtual CPU devices and the exact same
shard_map code executes on them.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
