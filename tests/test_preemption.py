"""Preemption tolerance for the batch path [ISSUE 4].

Three claims, pinned at increasing levels of realism:

1. **Shared heal machinery** (`parallel/self_heal.py`): bounded
   jittered backoff, probe -> fixed-width reshard over the spare pool,
   retry bounds, and the loud HealExhaustedError when the pool runs
   dry.
2. **Elastic re-sharding is invisible in the numbers**: a device loss
   mid-SGD-run / mid-Monte-Carlo-sweep heals onto spare devices at the
   same logical width and the final params/estimates are bit-identical
   to the fault-free run — values depend on (step/rep, logical shard)
   fold chains, never on physical placement.
3. **SIGKILL-mid-epoch resume is bit-identical**: a REAL subprocess is
   SIGKILLed by a chaos schedule right after a checkpoint lands;
   rerunning with ``--resume`` finishes the job and the final
   params/estimates equal the uninterrupted run's exactly (pairwise
   SGD, triplet SGD, and the mesh Monte-Carlo sweep).
"""

import dataclasses
import io
import json
import os
import signal
import subprocess
import sys
from contextlib import redirect_stdout

import numpy as np
import pytest

from tuplewise_tpu.data import make_gaussians
from tuplewise_tpu.harness.variance import (
    VarianceConfig, run_variance_experiment,
)
from tuplewise_tpu.models.pairwise_sgd import TrainConfig, train_pairwise
from tuplewise_tpu.models.scorers import LinearScorer
from tuplewise_tpu.models.triplet_sgd import (
    TripletTrainConfig, init_embed, train_triplet,
)
from tuplewise_tpu.parallel.self_heal import (
    Backoff, HealExhaustedError, MeshHealer,
)
from tuplewise_tpu.testing.chaos import FaultInjector, InjectedDeviceError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------------------------- #
# shared heal machinery                                                  #
# --------------------------------------------------------------------- #
class TestBackoff:
    def test_grows_and_caps(self):
        b = Backoff(base_s=0.1, cap_s=0.5, jitter=0.0)
        assert b.delay_s(1) == pytest.approx(0.1)
        assert b.delay_s(2) == pytest.approx(0.2)
        assert b.delay_s(5) == pytest.approx(0.5)     # capped

    def test_jitter_bounded_and_seeded(self):
        a = [Backoff(base_s=0.1, jitter=0.5, seed=7).delay_s(1)
             for _ in range(3)]
        b = [Backoff(base_s=0.1, jitter=0.5, seed=7).delay_s(1)
             for _ in range(3)]
        assert a == b                          # deterministic per seed
        for d in a:
            assert 0.1 <= d <= 0.15            # within the jitter band

    def test_rejects_bad_args(self):
        with pytest.raises(ValueError):
            Backoff(jitter=2.0)
        with pytest.raises(ValueError):
            Backoff().delay_s(0)


class TestMeshHealer:
    def _fast(self):
        return Backoff(base_s=0.0, cap_s=0.0, jitter=0.0)

    def test_retry_only_bound(self):
        """mesh=None degrades to retry-with-backoff; the bound
        surfaces the original error, retries are counted."""
        h = MeshHealer(None, backoff=self._fast())
        calls = []

        def boom():
            calls.append(1)
            raise RuntimeError("persistent")

        with pytest.raises(RuntimeError, match="persistent"):
            h.run(boom, retries=2)
        assert len(calls) == 3
        assert h.retries_total == 2
        assert h.reshard_events == 0

    def test_fixed_width_backfills_from_pool(self):
        import jax

        from tuplewise_tpu.parallel.mesh import make_mesh

        devs = jax.devices()
        mesh = make_mesh(2)
        inj = FaultInjector.from_spec({"faults": [
            {"point": "estimator", "on_call": 1, "action": "error",
             "dropped": [1]}]})
        h = MeshHealer(mesh, fixed_width=2, pool=list(devs),
                       chaos=inj, backoff=self._fast())
        healed = []

        n_calls = [0]

        def flaky():
            n_calls[0] += 1
            inj.fire("estimator")
            return 42

        out = h.run(flaky, retries=2, on_heal=lambda hh: healed.append(
            tuple(hh.mesh.devices.flat)))
        assert out == 42 and n_calls[0] == 2
        assert h.n_workers == 2                # width preserved
        assert h.reshard_events == 1
        # the dead device (old slot 1) was replaced by a spare
        assert devs[1] not in healed[0]
        assert len(healed[0]) == 2

    def test_pool_exhaustion_is_loud(self):
        from tuplewise_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(2)
        inj = FaultInjector.from_spec({"faults": [
            {"point": "estimator", "on_call": 1, "action": "error",
             "dropped": [0]}]})
        # pool == the mesh's own devices: losing one cannot sustain
        # width 2 -> loud HealExhaustedError, no silent narrowing
        h = MeshHealer(mesh, fixed_width=2, chaos=inj,
                       backoff=self._fast())

        def flaky():
            inj.fire("estimator")
            return 0

        with pytest.raises(HealExhaustedError, match="resume"):
            h.run(flaky, retries=3)

    def test_shrink_policy_drops_to_survivors(self):
        from tuplewise_tpu.parallel.mesh import make_mesh

        inj = FaultInjector.from_spec({"faults": [
            {"point": "estimator", "on_call": 1, "action": "error",
             "dropped": [0]}]})
        h = MeshHealer(make_mesh(2), chaos=inj, backoff=self._fast())

        def flaky():
            inj.fire("estimator")
            return 1

        assert h.run(flaky, retries=1) == 1
        assert h.n_workers == 1                # serving semantics


# --------------------------------------------------------------------- #
# elastic re-sharding: bit-identity under device loss                    #
# --------------------------------------------------------------------- #
@pytest.fixture(scope="module")
def train_data():
    return make_gaussians(128, 128, dim=4, separation=1.0, seed=0)


def _drop_spec(point, on_call, dropped):
    return FaultInjector.from_spec({"faults": [
        {"point": point, "on_call": on_call, "action": "error",
         "dropped": list(dropped)}]})


class TestElasticTraining:
    def test_pairwise_device_loss_bit_identical(self, train_data,
                                                tmp_path):
        Xp, Xn = train_data
        scorer = LinearScorer(dim=4)
        cfg = TrainConfig(kernel="logistic", lr=0.2, steps=10,
                          n_workers=2, repartition_every=4, tile=32)
        ref_p, ref_h = train_pairwise(scorer, scorer.init(0), Xp, Xn,
                                      cfg)
        inj = _drop_spec("train_step", 2, [1])
        p, h = train_pairwise(
            scorer, scorer.init(0), Xp, Xn, cfg, chaos=inj,
            checkpoint_path=str(tmp_path / "p.npz"), checkpoint_every=4,
            retry_backoff_s=0.001)
        for k in ref_p:
            np.testing.assert_array_equal(p[k], ref_p[k])
        np.testing.assert_array_equal(h["loss"], ref_h["loss"])
        assert h["recovery"]["reshard_events"] >= 1
        assert h["recovery"]["mesh_workers"] == 2   # width preserved

    def test_triplet_device_loss_bit_identical(self, train_data):
        Xc, Xo = train_data
        cfg = TripletTrainConfig(steps=8, n_workers=2,
                                 triplets_per_worker=256,
                                 repartition_every=4)
        ref_p, ref_h = train_triplet(init_embed(4, 3, 0), Xc, Xo, cfg)
        inj = _drop_spec("train_step", 1, [0])
        p, h = train_triplet(init_embed(4, 3, 0), Xc, Xo, cfg,
                             chaos=inj, retry_backoff_s=0.001)
        np.testing.assert_array_equal(p["W"], ref_p["W"])
        np.testing.assert_array_equal(h["loss"], ref_h["loss"])
        assert h["recovery"]["reshard_events"] >= 1

    def test_exhausted_pool_raises_not_narrows(self, train_data):
        """Chaos kills 7 of 8 devices across retries: the trainer must
        fail loudly (resume-from-checkpoint territory), never silently
        continue at a different logical width."""
        import jax

        if jax.device_count() != 8:
            pytest.skip("needs the 8-device CPU mesh")
        Xp, Xn = train_data
        scorer = LinearScorer(dim=4)
        cfg = TrainConfig(kernel="logistic", steps=4, n_workers=8,
                          repartition_every=2, tile=32)
        inj = FaultInjector.from_spec({"faults": [
            {"point": "train_step", "on_call": k, "action": "error",
             "dropped": [1]} for k in (1, 2)]})
        with pytest.raises(HealExhaustedError):
            train_pairwise(scorer, scorer.init(0), Xp, Xn, cfg,
                           chaos=inj, retry_backoff_s=0.001)


class TestElasticMonteCarlo:
    CFG = VarianceConfig(kernel="auc", scheme="local", backend="mesh",
                         n_pos=256, n_neg=256, n_workers=2, n_reps=8,
                         seed=3)

    def test_device_loss_mid_sweep_bit_identical(self, tmp_path):
        """The acceptance schedule: one device loss mid-sweep; the
        elastic re-shard completes the job over the survivors, results
        bit-identical, reshard_events >= 1 in the result record."""
        ref = run_variance_experiment(self.CFG)
        inj = _drop_spec("mesh_mc", 4, [1])
        res = run_variance_experiment(
            self.CFG, chaos=inj,
            checkpoint_path=str(tmp_path / "v.npz"), checkpoint_every=3)
        assert res["mean"] == ref["mean"]
        assert res["variance"] == ref["variance"]
        assert res["recovery"]["reshard_events"] >= 1
        assert res["recovery"]["retries_total"] >= 1
        assert res["recovery"]["mesh_workers"] == 2
        assert res["recovery"]["chaos"]["fired"] == {"mesh_mc": 1}

    def test_nonmesh_backend_shares_retry_discipline(self):
        cfg = dataclasses.replace(self.CFG, backend="jax",
                                  scheme="incomplete", n_pairs=200)
        ref = run_variance_experiment(cfg)
        inj = FaultInjector.from_spec({"faults": [
            {"point": "mc_chunk", "on_call": 1, "action": "error"}]})
        res = run_variance_experiment(cfg, chaos=inj)
        assert res["mean"] == ref["mean"]
        assert res["recovery"]["retries_total"] == 1
        assert res["recovery"]["reshard_events"] == 0

    def test_estimator_level_heal(self):
        """Estimator(heal_retries=...) on a mesh backend: a failed
        scheme call heals at the same shard count and returns the
        bit-identical value."""
        from tuplewise_tpu.estimators.estimator import Estimator

        rng = np.random.default_rng(0)
        s1 = rng.standard_normal(128) + 1.0
        s2 = rng.standard_normal(128)
        ref = Estimator("auc", backend="mesh", n_workers=2).complete(
            s1, s2)
        inj = _drop_spec("estimator", 1, [1])
        est = Estimator("auc", backend="mesh", n_workers=2,
                        heal_retries=2, chaos=inj)
        assert est.complete(s1, s2) == ref
        assert est._healer.reshard_events == 1
        assert est.backend.n_shards == 2

    def test_retry_bound_surfaces_persistent_failure(self):
        inj = FaultInjector.from_spec({"faults": [
            {"point": "mc_chunk", "on_call": k, "action": "error"}
            for k in range(1, 6)]})
        cfg = dataclasses.replace(self.CFG, backend="jax",
                                  scheme="incomplete", n_pairs=100,
                                  n_reps=2)
        with pytest.raises(InjectedDeviceError):
            run_variance_experiment(cfg, chaos=inj, heal_retries=2)


# --------------------------------------------------------------------- #
# harness sweep resume (in-process)                                      #
# --------------------------------------------------------------------- #
class TestTripletExperimentResume:
    def test_per_class_resume_bit_identical(self, tmp_path):
        from tuplewise_tpu.harness.triplet_experiment import (
            triplet_mnist_statistic,
        )

        kw = dict(backend="jax", n=300, n_pairs=500, seed=1)
        ref = triplet_mnist_statistic(**kw)
        p = str(tmp_path / "t.npz")
        # interrupt after 3 classes (sigkill is subprocess territory;
        # in-process the injector raises at the checkpoint hook)
        inj = FaultInjector.from_spec({"faults": [
            {"point": "checkpoint", "on_call": 3, "action": "error"}]})
        with pytest.raises(Exception):
            triplet_mnist_statistic(checkpoint_path=p, chaos=inj, **kw)
        res = triplet_mnist_statistic(checkpoint_path=p, **kw)
        assert res["recovery"]["resumed_from"] == 3
        assert res["per_class"] == ref["per_class"]
        assert res["mean"] == ref["mean"]


# --------------------------------------------------------------------- #
# distributed bring-up retry                                             #
# --------------------------------------------------------------------- #
class TestDistInitRetry:
    def test_bring_up_retries_then_succeeds(self, monkeypatch):
        import jax

        from tuplewise_tpu.parallel import distributed

        calls = []

        def fake_init(**kw):
            calls.append(kw)
            if len(calls) == 1:
                raise RuntimeError("coordinator not up yet")

        monkeypatch.setattr(jax.distributed, "initialize", fake_init)
        ok = distributed.initialize(
            coordinator_address="localhost:1", num_processes=1,
            process_id=0, retries=2, retry_backoff_s=0.0)
        assert ok and len(calls) == 2

    def test_chaos_hook_fires(self, monkeypatch):
        import jax

        from tuplewise_tpu.parallel import distributed

        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda **kw: None)
        inj = FaultInjector.from_spec({"faults": [
            {"point": "dist_init", "on_call": 1, "action": "error"}]})
        ok = distributed.initialize(
            coordinator_address="localhost:1", num_processes=1,
            process_id=0, retries=1, retry_backoff_s=0.0, chaos=inj)
        assert ok and inj.snapshot()["fired"] == {"dist_init": 1}


# --------------------------------------------------------------------- #
# SIGKILL-mid-epoch --resume (real subprocess kill)                      #
# --------------------------------------------------------------------- #
def _cli_env():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    flags = env.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    return env


def _run_cli(args, expect_kill=False, timeout=240):
    p = subprocess.run(
        [sys.executable, "-m", "tuplewise_tpu.harness.cli"] + args,
        capture_output=True, text=True, env=_cli_env(), cwd=REPO,
        timeout=timeout)
    if expect_kill:
        assert p.returncode == -signal.SIGKILL, (
            f"expected SIGKILL death, got rc={p.returncode}\n"
            f"{p.stderr[-2000:]}")
        return None
    assert p.returncode == 0, p.stderr[-2000:]
    return json.loads(p.stdout.strip().splitlines()[-1])


def _run_inproc(args):
    """The uninterrupted reference run, in-process (spares a third
    subprocess + jax cold start per scenario)."""
    from tuplewise_tpu.harness.cli import main

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert main(args) == 0
    return json.loads(buf.getvalue().strip().splitlines()[-1])


_KILL_AFTER_2ND_CHECKPOINT = json.dumps({"faults": [
    {"point": "checkpoint", "on_call": 2, "action": "sigkill"}]})

# (subcommand args, the fields that must match bit-for-bit)
_SCENARIOS = [
    pytest.param(
        ["train", "--dataset", "gaussians", "--n", "256", "--steps",
         "8", "--n-workers", "2"],
        ["params_sha256", "auc_test", "loss_last"], id="pairwise-sgd"),
    pytest.param(
        ["train-triplet", "--n", "128", "--dim", "4", "--embed-dim",
         "3", "--steps", "8", "--n-workers", "2",
         "--triplets-per-worker", "128"],
        ["params_sha256", "triplet_acc", "loss_last"],
        id="triplet-sgd"),
    pytest.param(
        ["variance", "--backend", "mesh", "--scheme", "local",
         "--n-pos", "128", "--n-neg", "128", "--n-workers", "2",
         "--n-reps", "6", "--seed", "3"],
        ["mean", "variance"], id="mesh-mc"),
]


class TestSigkillResume:
    @pytest.mark.parametrize("args,fields", _SCENARIOS)
    def test_sigkill_mid_run_resume_bit_identical(self, args, fields,
                                                  tmp_path):
        """The acceptance criterion, end to end: a chaos schedule
        SIGKILLs the CLI process right after its 2nd checkpoint lands
        (mid-epoch: more work remained); rerunning with --resume
        completes the job; final params/estimates are bit-identical to
        the uninterrupted run."""
        ck = str(tmp_path / "ck.npz")
        ref = _run_inproc(list(args))
        _run_cli(args + ["--checkpoint", ck, "--checkpoint-every", "2",
                         "--chaos-spec", _KILL_AFTER_2ND_CHECKPOINT],
                 expect_kill=True)
        assert os.path.exists(ck), "no checkpoint survived the kill"
        res = _run_cli(args + ["--checkpoint", ck,
                               "--checkpoint-every", "2", "--resume"])
        for f in fields:
            assert res[f] == ref[f], (f, res[f], ref[f])
        assert res["recovery"]["resumed_from"] > 0

    def test_without_resume_flag_starts_fresh(self, tmp_path):
        """--resume is explicit intent: a rerun WITHOUT it must discard
        the stale checkpoint and start a fresh run (resumed_from == 0),
        never continue silently."""
        ck = str(tmp_path / "ck.npz")
        args = ["train", "--dataset", "gaussians", "--n", "256",
                "--steps", "6", "--n-workers", "2", "--checkpoint", ck,
                "--checkpoint-every", "2"]
        _run_inproc(list(args))                      # leaves ck behind
        res = _run_inproc(list(args))                # no --resume
        assert res["recovery"]["resumed_from"] == 0
        res = _run_inproc(list(args) + ["--resume"])  # explicit intent
        assert res["recovery"]["resumed_from"] == 6

    @pytest.mark.slow
    def test_randomized_sigkill_soak(self, tmp_path):
        """Randomized-but-reproducible kill points: wherever the
        SIGKILL lands, --resume reproduces the straight run."""
        args = ["train", "--dataset", "gaussians", "--n", "256",
                "--steps", "12", "--n-workers", "2"]
        ref = _run_inproc(list(args))
        rng = np.random.default_rng(17)
        for trial in range(3):
            ck = str(tmp_path / f"soak{trial}.npz")
            kill_at = int(rng.integers(1, 6))
            spec = json.dumps({"faults": [
                {"point": "checkpoint", "on_call": kill_at,
                 "action": "sigkill"}]})
            _run_cli(args + ["--checkpoint", ck, "--checkpoint-every",
                             "2", "--chaos-spec", spec],
                     expect_kill=True)
            res = _run_cli(args + ["--checkpoint", ck,
                                   "--checkpoint-every", "2",
                                   "--resume"])
            assert res["params_sha256"] == ref["params_sha256"], (
                trial, kill_at)
