"""2-D (dcn x ici) hierarchical mesh [SURVEY §5.8 multi-host design].

Ring invariance must hold on the double ring exactly as on the flat
ring: the (2, 4) virtual mesh's complete U equals the single-device /
oracle value for any shard layout, and every scheme stays unbiased.
"""

import jax
import numpy as np
import pytest

from tuplewise_tpu import Estimator
from tuplewise_tpu.data import make_gaussians

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 (virtual) devices"
)


@pytest.fixture(scope="module")
def mesh2d():
    from tuplewise_tpu.parallel.mesh import make_mesh_2d

    return make_mesh_2d(2, 4)


@pytest.fixture(scope="module")
def scores():
    X, Y = make_gaussians(1600, 1300, dim=1, separation=1.0, seed=21)
    return X[:, 0], Y[:, 0]


@pytest.fixture(scope="module")
def est2d(mesh2d):
    return Estimator("auc", backend="mesh", mesh=mesh2d,
                     tile_a=64, tile_b=64)


class TestDoubleRingInvariance:
    def test_complete_matches_oracle(self, scores, est2d):
        s1, s2 = scores
        ref = Estimator("auc", backend="numpy").complete(s1, s2)
        assert abs(est2d.complete(s1, s2) - ref) < 1e-6

    def test_complete_ragged(self, scores, est2d):
        s1, s2 = scores
        s1, s2 = s1[:1237], s2[:1011]
        ref = Estimator("auc", backend="numpy").complete(s1, s2)
        assert abs(est2d.complete(s1, s2) - ref) < 1e-6

    def test_complete_pallas_double_ring(self, scores, mesh2d):
        s1, s2 = scores
        s1, s2 = s1[:1237], s2[:1011]
        ref = Estimator("auc", backend="numpy").complete(s1, s2)
        got = Estimator("auc", backend="mesh", mesh=mesh2d,
                        tile_a=64, tile_b=64,
                        impl="pallas").complete(s1, s2)
        assert abs(got - ref) < 1e-6

    def test_one_sample_complete(self, mesh2d):
        rng = np.random.default_rng(2)
        A = rng.standard_normal((300, 3))
        ref = Estimator("scatter", backend="numpy").complete(A)
        got = Estimator("scatter", backend="mesh", mesh=mesh2d,
                        tile_a=64, tile_b=64).complete(A)
        assert abs(got - ref) / abs(ref) < 1e-5

    def test_triplet_complete_hier_double_ring(self, mesh2d):
        """Degree-3 on the (2, 4) mesh: the triple-nested hierarchical
        ring must reproduce the oracle exactly, mirroring the 1-D
        double-ring test [BASELINE config 4 on a 2-D mesh]."""
        rng = np.random.default_rng(1)
        X = rng.standard_normal((48, 3))
        Y = rng.standard_normal((40, 3))
        ref = Estimator("triplet_indicator", backend="numpy").complete(X, Y)
        got = Estimator("triplet_indicator", backend="mesh", mesh=mesh2d,
                        triplet_tile=8).complete(X, Y)
        assert abs(got - ref) < 1e-6

    def test_triplet_complete_hier_ragged(self, mesh2d):
        rng = np.random.default_rng(4)
        X = rng.standard_normal((37, 3))   # not multiples of 8 shards
        Y = rng.standard_normal((29, 3))
        ref = Estimator("triplet_hinge", backend="numpy").complete(X, Y)
        got = Estimator("triplet_hinge", backend="mesh", mesh=mesh2d,
                        triplet_tile=8).complete(X, Y)
        assert abs(got - ref) / max(abs(ref), 1) < 1e-5


class TestSchemesOn2D:
    def test_local_average_unbiased(self, scores, est2d):
        s1, s2 = scores
        u_n = Estimator("auc", backend="numpy").complete(s1, s2)
        vals = [est2d.local_average(s1, s2, seed=m) for m in range(30)]
        se = np.std(vals) / np.sqrt(len(vals)) + 1e-6
        assert abs(np.mean(vals) - u_n) < 5 * se

    def test_repartitioned_runs(self, scores, est2d):
        s1, s2 = scores
        v = est2d.repartitioned(s1, s2, n_rounds=3, seed=0)
        assert 0.0 < v < 1.0

    def test_incomplete_unbiased(self, scores, est2d):
        s1, s2 = scores
        u_n = Estimator("auc", backend="numpy").complete(s1, s2)
        vals = [
            est2d.incomplete(s1, s2, n_pairs=4000, seed=m)
            for m in range(40)
        ]
        se = np.std(vals) / np.sqrt(len(vals)) + 1e-6
        assert abs(np.mean(vals) - u_n) < 5 * se

    def test_dropped_workers(self, scores, est2d):
        s1, s2 = scores
        full = est2d.local_average(s1, s2, seed=0)
        drop = est2d.local_average(s1, s2, seed=0, dropped_workers=(6,))
        assert full != drop

    def test_n_workers_is_total_shards(self, est2d):
        assert est2d.n_workers == 8

    def test_arbitrary_axis_names(self, scores):
        """Regression: the backend must take axis names from the mesh
        itself — a user mesh named ('hosts', 'chips') used to hit
        'unbound axis name: w' at trace time."""
        s1, s2 = scores
        mesh = jax.make_mesh((2, 4), ("hosts", "chips"))
        est = Estimator("auc", backend="mesh", mesh=mesh,
                        tile_a=64, tile_b=64)
        ref = Estimator("auc", backend="numpy").complete(s1, s2)
        assert abs(est.complete(s1, s2) - ref) < 1e-6

    def test_3d_mesh_rejected(self):
        devs = np.array(jax.devices()).reshape(2, 2, 2)
        mesh = jax.sharding.Mesh(devs, ("a", "b", "c"))
        with pytest.raises(ValueError, match="1-D or 2-D"):
            Estimator("auc", backend="mesh", mesh=mesh)
