"""obs.health [ISSUE 7]: CI-width monitor vs offline NumPy, drift
detection, shard balance, and the engine/index integration."""

import math

import numpy as np
import pytest

from tuplewise_tpu.obs.flight import FlightRecorder
from tuplewise_tpu.obs.health import (
    DriftDetector, EstimateHealth, shard_balance,
)
from tuplewise_tpu.utils.profiling import MetricsRegistry


class TestEstimateHealth:
    def test_matches_offline_numpy_recomputation(self):
        rng = np.random.default_rng(0)
        h = EstimateHealth(retain_terms=True)
        all_terms = []
        for _ in range(40):
            batch = rng.choice([0.0, 0.5, 1.0],
                               size=rng.integers(1, 400),
                               p=[0.2, 0.1, 0.7])
            h.update(batch)
            all_terms.append(batch)
        terms = np.concatenate(all_terms)
        assert h.n == terms.size
        assert h.mean == pytest.approx(float(terms.mean()), abs=1e-12)
        assert h.variance() == pytest.approx(
            float(np.var(terms, ddof=1)), rel=1e-10)
        se = math.sqrt(np.var(terms, ddof=1) / terms.size)
        assert h.std_error() == pytest.approx(se, rel=1e-10)
        assert h.ci_width() == pytest.approx(2 * 1.959963984540054 * se,
                                             rel=1e-10)
        chk = h.offline_check()
        assert chk["abs_err"]["variance"] < 1e-12
        assert chk["abs_err"]["ci_width"] < 1e-12

    def test_ci_width_shrinks_with_n(self):
        rng = np.random.default_rng(1)
        h = EstimateHealth()
        h.update(rng.random(100))
        w1 = h.ci_width()
        for _ in range(99):
            h.update(rng.random(100))
        assert h.ci_width() < w1 / 5     # ~ sqrt(100) shrink

    def test_batch_ci_honors_batch_structure(self):
        h = EstimateHealth()
        # identical batch means -> zero batch-mean variance even
        # though within-batch variance is large
        for _ in range(10):
            h.update(np.array([0.0, 1.0]))
        assert h.variance() > 0
        assert h.batch_std_error() == pytest.approx(0.0, abs=1e-15)

    def test_undefined_below_two_terms(self):
        h = EstimateHealth()
        assert h.variance() is None and h.ci_width() is None
        h.update(np.array([0.5]))
        assert h.variance() is None
        h.update(np.array([], dtype=float))
        assert h.n == 1

    def test_gauges_exported(self):
        reg = MetricsRegistry()
        h = EstimateHealth(metrics=reg)
        h.update(np.array([0.0, 0.5, 1.0, 1.0]))
        snap = reg.snapshot()
        assert snap["estimate_terms"]["value"] == 4
        assert snap["estimate_ci_width"]["value"] == \
            pytest.approx(h.ci_width())

    def test_offline_check_requires_retention(self):
        with pytest.raises(RuntimeError):
            EstimateHealth().offline_check()

    def test_confidence_validation(self):
        with pytest.raises(ValueError):
            EstimateHealth(confidence=1.5)


class TestStreamingIntegration:
    def test_streaming_terms_feed_monitor_and_match_offline(self):
        from tuplewise_tpu.serving.streaming import StreamingIncompleteU

        h = EstimateHealth(retain_terms=True)
        s = StreamingIncompleteU(budget=16, reservoir=256, seed=0,
                                 health=h)
        rng = np.random.default_rng(2)
        for i in range(30):
            n = int(rng.integers(1, 60))
            labels = rng.random(n) < 0.5
            s.extend(rng.standard_normal(n) + labels, labels)
        # the monitor saw exactly the terms the estimate is built from
        assert h.n == s.n_terms
        assert h.mean == pytest.approx(s.estimate(), rel=1e-12)
        chk = h.offline_check()
        assert chk["abs_err"]["variance"] < 1e-10
        assert chk["abs_err"]["ci_width"] < 1e-10
        assert "health" in s.state()

    def test_facade_passthrough(self):
        from tuplewise_tpu.estimators import StreamingEstimator

        h = EstimateHealth()
        est = StreamingEstimator(budget=8, reservoir=64, engine="numpy",
                                 health=h)
        rng = np.random.default_rng(3)
        # several batches: arrivals only pair with PAST history, so a
        # single extend against empty reservoirs spends no terms
        for _ in range(4):
            labels = rng.random(50) < 0.5
            est.extend(rng.standard_normal(50) + labels, labels)
        rep = est.health_report()
        assert rep is not None and rep["n_terms"] == h.n > 0
        assert StreamingEstimator(engine="numpy").health_report() is None


class TestDriftDetector:
    def test_transition_fires_once_with_flight_and_gauges(self):
        reg = MetricsRegistry()
        fl = FlightRecorder()
        d = DriftDetector(window=4, threshold=0.1, metrics=reg,
                          flight=fl)
        for _ in range(4):
            assert not d.observe(0.5, 0.5)
        fired = [d.observe(0.8, 0.5) for _ in range(3)]
        assert fired == [False, True, False]   # mean crosses at #2
        assert d.alerts == 1
        assert len(fl.events("health_drift")) == 1
        snap = reg.snapshot()
        assert snap["drift_alerts_total"]["value"] == 1
        # window holds [0, 0.3, 0.3, 0.3] after the third bad pair
        assert snap["estimate_drift"]["value"] == pytest.approx(0.225)
        # recovery clears the live state, keeps the alert count
        for _ in range(8):
            d.observe(0.5, 0.5)
        assert not d.drifting and d.alerts == 1

    def test_min_fill_suppresses_early_noise(self):
        d = DriftDetector(window=8, threshold=0.01)
        assert not d.observe(1.0, 0.0)     # huge gap, window not full
        assert not d.drifting

    def test_validation(self):
        with pytest.raises(ValueError):
            DriftDetector(window=0)
        with pytest.raises(ValueError):
            DriftDetector(threshold=0.0)


class TestShardBalance:
    def test_balanced_and_skewed(self):
        b = shard_balance([100, 100, 100, 100])
        assert b["skew"] == pytest.approx(1.0)
        assert b["cv"] == pytest.approx(0.0)
        b = shard_balance([300, 50, 50, 0])
        assert b["skew"] == pytest.approx(3.0)
        assert b["max"] == 300 and b["min"] == 0
        assert b["cv"] > 1.0

    def test_empty(self):
        assert shard_balance([])["skew"] == 1.0
        assert shard_balance([0, 0])["skew"] == 1.0


class TestEngineIntegration:
    def test_replay_exports_health_gauges_matching_offline(self):
        """The acceptance pair [ISSUE 7]: the engine's live CI-width
        gauge equals an offline recomputation driven by the same
        stream/seed through a term-retaining monitor."""
        from tuplewise_tpu.serving import ServingConfig
        from tuplewise_tpu.serving.replay import make_stream, replay
        from tuplewise_tpu.serving.streaming import StreamingIncompleteU

        scores, labels = make_stream(1200, seed=7)
        cfg = ServingConfig(policy="block", compact_every=512,
                            budget=16, reservoir=256, seed=7,
                            max_batch=64)
        # max_inflight=1 serializes requests, so every micro-batch is
        # exactly one 64-event chunk — the offline twin below can then
        # replay the identical batch slicing
        rec = replay(scores, labels, config=cfg, chunk=64,
                     max_inflight=1)
        snap_terms = rec["incomplete_pairs"]
        h = EstimateHealth(retain_terms=True)
        s = StreamingIncompleteU(budget=16, reservoir=256, seed=7,
                                 health=h)
        for i in range(0, 1200, 64):
            s.extend(scores[i:i + 64], labels[i:i + 64])
        assert h.n == snap_terms == s.n_terms
        chk = h.offline_check()
        assert chk["abs_err"]["ci_width"] < 1e-10

    def test_engine_stats_carry_drift_state(self):
        from tuplewise_tpu.serving import MicroBatchEngine

        with MicroBatchEngine(policy="block", budget=4,
                              reservoir=64) as eng:
            rng = np.random.default_rng(0)
            for _ in range(3):    # separate batches: terms need history
                labels = rng.random(40) < 0.5
                eng.insert(rng.standard_normal(40) + labels,
                           labels).result(10)
            st = eng.flush()
            assert "drift" in st
            assert st["drift"]["alerts"] == 0
            assert st["streaming"]["health"]["n_terms"] > 0
            snap = st["metrics"]
            assert snap["estimate_ci_width"]["value"] > 0

    def test_health_off_switch(self):
        from tuplewise_tpu.serving import MicroBatchEngine

        with MicroBatchEngine(policy="block", health=False) as eng:
            eng.insert([1.0, -1.0], [1, 0]).result(10)
            st = eng.flush()
            assert "drift" not in st
            assert "health" not in st["streaming"]
            assert "estimate_ci_width" not in st["metrics"]


class TestShardedIndexGauges:
    def test_shard_occupancy_and_skew_gauges(self):
        from tuplewise_tpu.serving.index import ExactAucIndex

        idx = ExactAucIndex(engine="jax", shards=2, compact_every=64)
        rng = np.random.default_rng(0)
        for i in range(0, 512, 64):
            labels = rng.random(64) < 0.5
            idx.insert_batch(
                rng.standard_normal(64).astype(np.float32), labels)
        occ = idx.shard_occupancy()
        assert len(occ) == 2
        # placed rows = base + delta of both classes
        placed = sum(
            len(side.placed_base if side.placed_base is not None
                else side.base) + len(side.delta_run)
            for side in (idx._pos, idx._neg))
        assert sum(occ) == placed > 0
        snap = idx.metrics.snapshot()
        assert snap["shard_skew"]["value"] >= 1.0
        # contiguous-slice placement: within one row of perfect
        assert snap["shard_skew"]["value"] < 1.1
        idx.close()
