"""Fixture + repo tests for the host-cost certification tier
[ISSUE 15]: per-root cost certificates (counter families, loop
classification, interprocedural multiplicity propagation), the
committed-budget diff (grow fails naming root/site/budget line,
shrink ratchets), certificate schema, the root-missing finding, and
the runner satellites — epoch-keyed parse cache, ``--diff`` scoping,
and the concurrent pass runner.
"""

import json
import os
import subprocess
import sys

import pytest

from tuplewise_tpu.analysis import hotpath, modgraph
from tuplewise_tpu.analysis.cache import ParseCache, compute_epoch
from tuplewise_tpu.analysis.core import ModuleInfo, ModuleSet

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUDGET = os.path.join(REPO, "tuplewise_tpu", "analysis",
                      "hotpath_budget.toml")


def ms_of(src: str, path: str = "tuplewise_tpu/fixture.py",
          **extra) -> ModuleSet:
    return ModuleSet.from_sources({path: src, **extra})


FIXTURE = '''
import threading
import numpy as np


def helper(r):
    return [r, r]


class Engine:
    def __init__(self):
        self._lock = threading.Lock()

    def apply(self, run, groups):
        wave_buf = []
        arr = np.asarray(run)
        with self._lock:
            n = len(run)
        for r in run:
            d = {"v": r}
            wave_buf.append(helper(r))
        for tid, reqs in groups:
            seen = {tid}
        out = sharded_counts(arr, n)
        return wave_buf

    def quiet(self, run):
        cfg = (1, 2)
        for _side in ("pos", "neg"):
            pass
        return cfg
'''

ROOT_APPLY = (("tuplewise_tpu/fixture.py", "Engine", "apply"),)
ROOT_QUIET = (("tuplewise_tpu/fixture.py", "Engine", "quiet"),)


@pytest.fixture(scope="module")
def fixture_cert():
    return hotpath.certificates(ms_of(FIXTURE), roots=ROOT_APPLY)


def test_certificate_schema(fixture_cert):
    assert fixture_cert["missing"] == []
    (e,) = fixture_cert["roots"]
    assert e["root"] == "Engine.apply"
    assert e["file"] == "tuplewise_tpu/fixture.py"
    assert e["line"] > 0
    assert e["loop_class"] in ("O(1)", "O(tenants)", "O(events)")
    assert isinstance(e["counters"], dict)
    for key, v in e["counters"].items():
        counter, _, suffix = key.rpartition("_per_")
        assert counter in hotpath.COUNTERS
        assert v > 0
        assert key in e["sites"] and len(e["sites"][key]) >= 1


def test_loop_classification_and_counters(fixture_cert):
    (e,) = fixture_cert["roots"]
    c = e["counters"]
    # the per-event dict display inside `for r in run`
    assert c["alloc_per_event"] >= 1
    # wave_buf = [] at function level
    assert c["alloc_per_wave"] >= 1
    # the {tid} set inside the `for tid, reqs in groups` tenant loop
    assert c["alloc_per_tenant"] >= 1
    # np.asarray at wave level
    assert c["np_alloc_per_wave"] >= 1
    # with self._lock at wave level
    assert c["lock_per_wave"] == 1
    # sharded_counts(...) is device dispatch
    assert c["dispatch_per_wave"] == 1
    assert e["loop_class"] == "O(events)"


def test_interprocedural_multiplicity(fixture_cert):
    (e,) = fixture_cert["roots"]
    # helper() is called inside the per-event loop: its [r, r] display
    # must bill per event, and show up in the site evidence
    sites = e["sites"].get("alloc_per_event", [])
    assert any("helper" in s for s in sites), sites


def test_quiet_root_is_o1():
    cert = hotpath.certificates(ms_of(FIXTURE), roots=ROOT_QUIET)
    (e,) = cert["roots"]
    # constant-tuple iteration and no per-event work: O(1), no alloc
    # beyond the wave-level tuple display
    assert e["loop_class"] == "O(1)"
    assert "alloc_per_event" not in e["counters"]


def test_missing_root_finding():
    cert = hotpath.certificates(
        ms_of(FIXTURE),
        roots=(("tuplewise_tpu/fixture.py", "Engine", "vanished"),))
    assert cert["missing"] == [{"root": "Engine.vanished",
                               "file": "tuplewise_tpu/fixture.py"}]
    (f,) = hotpath.missing_findings(cert)
    assert f.rule == "hotpath-root-missing"
    assert f.symbol == "Engine.vanished"


# --------------------------------------------------------------------- #
# budget file: parse / format / diff semantics                           #
# --------------------------------------------------------------------- #

def test_budget_roundtrip(fixture_cert):
    text = hotpath.format_budget(fixture_cert)
    entries = hotpath.parse_budget(text)
    (e,) = fixture_cert["roots"]
    (b,) = entries
    assert b["root"] == e["root"]
    assert b["loop_class"] == e["loop_class"]
    for k, v in e["counters"].items():
        assert b[k] == v
    errors, shrinks = hotpath.compare_to_budget(fixture_cert, text)
    assert errors == [] and shrinks == []


def test_budget_malformed():
    with pytest.raises(hotpath.BudgetError):
        hotpath.parse_budget("[maxima]\nS = 2\n")
    with pytest.raises(hotpath.BudgetError):
        hotpath.parse_budget("[[root]]\nroot = \"x\"\n")  # no file
    errors, _ = hotpath.compare_to_budget(
        {"roots": [], "missing": []}, "[[oops]]\n")
    assert errors and "only [[root]]" in errors[0]


def _bump(cert, key, delta):
    import copy

    out = copy.deepcopy(cert)
    c = out["roots"][0]["counters"]
    c[key] = c.get(key, 0) + delta
    if c[key] <= 0:
        del c[key]
    return out


def test_budget_growth_fails_naming_root_site_and_line(fixture_cert):
    text = hotpath.format_budget(fixture_cert)
    grown = _bump(fixture_cert, "alloc_per_event", 1)
    errors, shrinks = hotpath.compare_to_budget(grown, text)
    assert len(errors) == 1 and shrinks == []
    msg = errors[0]
    assert "Engine.apply" in msg
    assert "alloc_per_event" in msg
    # the violated budget line is NAMED
    assert "hotpath_budget.toml:" in msg
    lineno = int(msg.split("hotpath_budget.toml:")[1].split(")")[0])
    assert text.splitlines()[lineno - 1].startswith("alloc_per_event")
    # contributing sites ride along
    assert "tuplewise_tpu/fixture.py" in msg


def test_budget_shrink_ratchets(fixture_cert):
    text = hotpath.format_budget(fixture_cert)
    shrunk = _bump(fixture_cert, "alloc_per_event", -1)
    errors, shrinks = hotpath.compare_to_budget(shrunk, text)
    assert errors == []
    assert shrinks and "alloc_per_event" in shrinks[0]


def test_budget_new_root_and_stale_root_fail(fixture_cert):
    import copy

    text = hotpath.format_budget(fixture_cert)
    extra = copy.deepcopy(fixture_cert)
    extra["roots"].append(dict(extra["roots"][0], root="Engine.new"))
    errors, _ = hotpath.compare_to_budget(extra, text)
    assert any("Engine.new" in e and "no committed budget" in e
               for e in errors)
    none = {"roots": [], "missing": []}
    errors, _ = hotpath.compare_to_budget(none, text)
    assert any("stale budget entry" in e for e in errors)


def test_budget_loop_class_worsening_fails(fixture_cert):
    import copy

    text = hotpath.format_budget(fixture_cert).replace(
        'loop_class = "O(events)"', 'loop_class = "O(1)"')
    errors, _ = hotpath.compare_to_budget(fixture_cert, text)
    assert any("loop class worsened" in e for e in errors)


def test_budget_missing_root_reported(fixture_cert):
    import copy

    cert = copy.deepcopy(fixture_cert)
    cert["missing"].append({"root": "Engine.gone",
                            "file": "tuplewise_tpu/fixture.py"})
    errors, _ = hotpath.compare_to_budget(
        cert, hotpath.format_budget(fixture_cert))
    assert any("Engine.gone" in e and "ROOTS" in e for e in errors)


# --------------------------------------------------------------------- #
# the real repo against the committed budget                             #
# --------------------------------------------------------------------- #

@pytest.fixture(scope="module")
def repo_ms():
    return ModuleSet.from_repo(REPO)


@pytest.fixture(scope="module")
def repo_cert(repo_ms):
    return hotpath.certificates(repo_ms)


def test_repo_all_roots_certified(repo_cert):
    assert repo_cert["missing"] == []
    names = {e["root"] for e in repo_cert["roots"]}
    assert names == {f"{cls}.{meth}" if cls else meth
                     for _p, cls, meth in hotpath.ROOTS}
    # the host-tax story the certificate exists to ratchet: the fleet
    # insert path pays per-event Python today
    fleet = next(e for e in repo_cert["roots"]
                 if e["root"] ==
                 "MultiTenantEngine._apply_insert_wave_ledgered")
    assert fleet["counters"].get("attr_hop_per_event", 0) > 0


def test_repo_certificate_matches_committed_budget(repo_cert):
    with open(BUDGET, "r", encoding="utf-8") as f:
        text = f.read()
    errors, shrinks = hotpath.compare_to_budget(repo_cert, text)
    assert errors == [], (
        "hotpath certificate drifted from the committed budget — a "
        "grown counter is new host cost on the request path (fix it "
        "or re-baseline with scripts/analysis_gate.py "
        "--update-hotpath-budget after review):\n" + "\n".join(errors))
    assert shrinks == [], (
        "counters shrank — run scripts/analysis_gate.py once to "
        "ratchet the committed budget down and commit it:\n"
        + "\n".join(shrinks))


def test_seeded_per_event_allocation_fails_budget(repo_ms, repo_cert):
    """The acceptance criterion, end to end: a per-event dict display
    + lock acquisition seeded into engine.py's resolve loop must fail
    the budget diff naming the root, the engine.py site, and the
    violated budget line."""
    path = "tuplewise_tpu/serving/engine.py"
    src = repo_ms.modules[path].source
    anchor = "        for r in run:\n            # a request the reaper"
    assert anchor in src, "engine resolve-loop anchor moved"
    seeded = src.replace(
        anchor,
        "        for r in run:\n"
        "            with self._lock:\n"
        "                _shadow = {\"n\": len(r.scores)}\n"
        + anchor, 1)
    mods = dict(repo_ms.modules)
    mods[path] = ModuleInfo(path, seeded)
    ms2 = ModuleSet({k: v for k, v in mods.items()},
                    texts=repo_ms.texts, root=repo_ms.root)
    cert2 = hotpath.certificates(ms2)
    with open(BUDGET, "r", encoding="utf-8") as f:
        errors, _ = hotpath.compare_to_budget(cert2, f.read())
    assert errors, "seeded per-event allocation went undetected"
    blob = "\n".join(errors)
    assert "MicroBatchEngine._apply_inserts_wave" in blob
    assert "tuplewise_tpu/serving/engine.py" in blob
    assert "hotpath_budget.toml:" in blob
    assert any("alloc_per_event" in e for e in errors)
    assert any("lock_per_event" in e for e in errors)


# --------------------------------------------------------------------- #
# runner satellites: epoch cache, --diff, concurrency                    #
# --------------------------------------------------------------------- #

def _mini_repo(tmp_path):
    adir = tmp_path / "tuplewise_tpu" / "analysis"
    adir.mkdir(parents=True)
    (adir / "waivers.toml").write_text("# v1\n")
    sub = tmp_path / "tuplewise_tpu" / "sub"
    sub.mkdir()
    (sub / "mod.py").write_text("def f():\n    return 1\n")
    return str(tmp_path)


def test_cache_epoch_waiver_edit_forces_cold_run(tmp_path):
    """[ISSUE 15 satellite bugfix] the regression the issue names:
    content-sha-only keys replayed stale state across a waivers.toml
    edit. The epoch folds the waiver/budget/checker digests into
    every key, so the edit must produce a COLD re-run."""
    root = _mini_repo(tmp_path)
    c1 = ParseCache(root, epoch=compute_epoch(root))
    ModuleSet.from_repo(root, cache=c1)
    assert c1.misses >= 1
    c2 = ParseCache(root, epoch=compute_epoch(root))
    ModuleSet.from_repo(root, cache=c2)
    assert c2.hits >= 1 and c2.misses == 0      # warm, same epoch
    (tmp_path / "tuplewise_tpu" / "analysis"
     / "waivers.toml").write_text("# v2 — edited waiver\n")
    c3 = ParseCache(root, epoch=compute_epoch(root))
    ModuleSet.from_repo(root, cache=c3)
    assert c3.hits == 0 and c3.misses >= 1      # cold re-run


def test_cache_epoch_tracks_checker_and_budget(tmp_path):
    root = _mini_repo(tmp_path)
    e1 = compute_epoch(root)
    (tmp_path / "tuplewise_tpu" / "analysis"
     / "hotpath_budget.toml").write_text("# budget\n")
    e2 = compute_epoch(root)
    assert e1 != e2
    (tmp_path / "tuplewise_tpu" / "analysis"
     / "newpass.py").write_text("# checker change\n")
    assert compute_epoch(root) != e2


def test_reverse_closure():
    ms = ModuleSet.from_sources({
        "tuplewise_tpu/a.py": "from tuplewise_tpu import b\n",
        "tuplewise_tpu/b.py": "from tuplewise_tpu import c\n",
        "tuplewise_tpu/c.py": "x = 1\n",
        "tuplewise_tpu/d.py": "y = 2\n",
    })
    scope = modgraph.reverse_closure(ms, {"tuplewise_tpu/c.py"})
    assert scope == {"tuplewise_tpu/a.py", "tuplewise_tpu/b.py",
                     "tuplewise_tpu/c.py"}
    assert "tuplewise_tpu/d.py" not in scope


def test_run_checks_diff_mode():
    from tuplewise_tpu.analysis.runner import run_checks

    report = run_checks(root=REPO, diff_ref="HEAD")
    assert report["diff"]["ref"] == "HEAD"
    assert "error" not in report["diff"]
    # scoped findings are a subset; stale waivers never fail a diff run
    assert report["unused_waivers"] == []
    assert report["ok"] is True, report["findings"]


def test_run_checks_timing_block():
    from tuplewise_tpu.analysis.runner import PASSES, run_checks

    report = run_checks(root=REPO)
    t = report["summary"]["timings"]
    assert t["jobs"] >= 1
    assert set(t["passes_s"]) == {name for name, _ in PASSES}
    assert t["total_s"] >= sum(t["passes_s"].values()) * 0.5
    assert report["hotpath_certificate"] is not None


def test_concurrent_runner_matches_serial():
    """--jobs 2 in a clean subprocess (fork safety: no jax in that
    process): same verdict, every pass ran, certificate present."""
    out = os.path.join(REPO, "results", "_check_jobs2.json")
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "tuplewise_tpu.harness.cli",
             "check", "--jobs", "2", "--out", out],
            cwd=REPO, capture_output=True, text=True, timeout=600)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        with open(out) as f:
            rep = json.load(f)
        assert rep["summary"]["timings"]["jobs"] == 2
        assert rep["ok"] is True
        from tuplewise_tpu.analysis.runner import PASSES

        assert set(rep["summary"]["per_pass"]) == {
            name for name, _ in PASSES}
        assert rep["hotpath_certificate"]["missing"] == []
    finally:
        if os.path.exists(out):
            os.unlink(out)
