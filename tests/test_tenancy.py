"""Multi-tenant serving fleet [ISSUE 8]: per-tenant bit-parity with
independent single-tenant engines (at S=1/2/4, under chaos heal, and
across SIGKILL recovery), the one-jitted-count witness, admission
control + weighted-fair scheduling, tenant lifecycle, and the
tenant-attributed close regression."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from tuplewise_tpu.serving.engine import (
    EngineClosedError, MicroBatchEngine, PoisonEventError, ServingConfig,
)
from tuplewise_tpu.serving.index import ExactAucIndex
from tuplewise_tpu.serving.replay import make_tenant_stream, replay_fleet
from tuplewise_tpu.serving.tenancy import (
    FleetRecoveryManager, MultiTenantEngine, TenancyConfig,
    TenantFleetIndex, TenantRejectedError, capture_fleet_snapshot_state,
    tenant_seed,
)
from tuplewise_tpu.testing.chaos import FaultInjector


def _tenant_streams(n_tenants, n_events, seed=0):
    rng = np.random.default_rng(seed)
    out = {}
    for k in range(n_tenants):
        labels = rng.random(n_events) < 0.5
        scores = rng.standard_normal(n_events) + 0.8 * labels
        out[f"t{k}"] = (scores, labels)
    return out


def _drive_pair(fleet, streams, *, chunk_rng_seed=1, singles=None,
                window=None, compact_every=64):
    """Feed the same per-tenant streams into the fleet (random
    coalesced multi-tenant batches) and into independent
    single-tenant indexes; returns the singles."""
    if singles is None:
        singles = {t: ExactAucIndex(window=window,
                                    compact_every=compact_every,
                                    engine="jax")
                   for t in streams}
    n = len(next(iter(streams.values()))[0])
    pos = {t: 0 for t in streams}
    rng = np.random.default_rng(chunk_rng_seed)
    while any(pos[t] < n for t in streams):
        items = []
        for t in streams:
            if pos[t] >= n or rng.random() > 0.7:
                continue
            k = int(rng.integers(1, 40))
            s, l = streams[t]
            items.append((t, s[pos[t]:pos[t] + k], l[pos[t]:pos[t] + k]))
            pos[t] += k
        if items:
            fleet.apply_inserts(items)
            for t, s, l in items:
                singles[t].insert_batch(s, l)
    return singles


class TestFleetParity:
    """Acceptance: T-tenant engine bit-identical to T independent
    single-tenant engines — wins2 AND AUC, at several mesh widths,
    windowed and unbounded."""

    @pytest.mark.parametrize("shards,window", [
        (None, None), (None, 100), (1, None), (2, 128), (4, 64),
    ])
    def test_wins2_bit_identical(self, shards, window):
        streams = _tenant_streams(5, 300, seed=2)
        fleet = TenantFleetIndex(window=window, compact_every=64,
                                 shards=shards)
        singles = _drive_pair(fleet, streams, window=window)
        for t in streams:
            assert fleet.wins2(t) == singles[t]._wins2, (shards, t)
            assert fleet.auc(t) == singles[t].auc(), (shards, t)

    def test_score_parity(self):
        streams = _tenant_streams(3, 200, seed=3)
        fleet = TenantFleetIndex(compact_every=32, shards=2)
        singles = _drive_pair(fleet, streams, compact_every=32)
        q = np.random.default_rng(4).standard_normal(13)
        ranks = fleet.apply_scores([(t, q) for t in streams])
        for i, t in enumerate(streams):
            np.testing.assert_array_equal(
                ranks[i], singles[t].score_batch(q))

    def test_oracle_values_roundtrip(self):
        streams = _tenant_streams(2, 150, seed=5)
        fleet = TenantFleetIndex(window=80, compact_every=16)
        singles = _drive_pair(fleet, streams, window=80,
                              compact_every=16)
        for t in streams:
            fp, fn = fleet.oracle_values(t)
            sp, sn = singles[t].oracle_values()
            np.testing.assert_array_equal(np.sort(fp), np.sort(sp))
            np.testing.assert_array_equal(np.sort(fn), np.sort(sn))


class TestOneJittedCall:
    """Acceptance: ONE jitted batched count serves each coalesced
    multi-tenant batch — call count scales with batches, never with
    the tenant mix; compile cache growth follows the bucket ladder."""

    def test_one_call_per_apply(self):
        streams = _tenant_streams(6, 120, seed=7)
        fleet = TenantFleetIndex(compact_every=1024)
        n_applies = 0
        pos = 0
        while pos < 120:
            k = min(30, 120 - pos)
            fleet.apply_inserts(
                [(t, s[pos:pos + k], l[pos:pos + k])
                 for t, (s, l) in streams.items()])
            n_applies += 1
            pos += k
        st = fleet.state()
        assert st["count_calls"] == n_applies
        # and the per-tenant query tally confirms the fan-in
        tq = fleet.metrics.snapshot()[
            "fleet_count_tenant_queries_total"]["value"]
        assert tq == n_applies * 6

    def test_calls_independent_of_tenant_count(self):
        """Same batches, 2 vs 6 tenants: identical call counts."""
        calls = {}
        for T in (2, 6):
            streams = _tenant_streams(T, 90, seed=8)
            fleet = TenantFleetIndex(compact_every=1024)
            pos = 0
            while pos < 90:
                fleet.apply_inserts(
                    [(t, s[pos:pos + 30], l[pos:pos + 30])
                     for t, (s, l) in streams.items()])
                pos += 30
            calls[T] = fleet.state()["count_calls"]
        assert calls[2] == calls[6] == 3

    def test_compile_cache_follows_ladder(self):
        """The jitted-kernel cache grows with the (T_bucket, cap,
        q_bucket) ladder, not with tenants x batches."""
        from tuplewise_tpu.parallel.sharded_counts import (
            tenant_count_local_fn,
        )

        before = tenant_count_local_fn.cache_info().currsize
        streams = _tenant_streams(5, 200, seed=9)
        fleet = TenantFleetIndex(compact_every=64)
        _drive_pair(fleet, streams)
        grown = tenant_count_local_fn.cache_info().currsize - before
        # 5 tenants x dozens of batches, yet only a handful of shapes
        assert 0 <= grown <= 6, grown


class TestChaosFleet:
    """[ISSUE 8 satellite] device loss + compactor crash during
    multi-tenant serving: per-tenant results bit-identical to
    independent single-tenant engines after heal."""

    def test_device_loss_and_compactor_crash_parity(self):
        spec = {"faults": [
            {"point": "sharded_count", "on_call": 3, "action": "error",
             "dropped": [1]},
            {"point": "compactor_build", "on_call": 1,
             "action": "error"},
            {"point": "place_base", "on_call": 4, "action": "error"},
        ]}
        chaos = FaultInjector.from_spec(spec)
        streams = _tenant_streams(4, 260, seed=11)
        fleet = TenantFleetIndex(window=128, compact_every=32,
                                 shards=2, chaos=chaos)
        singles = _drive_pair(fleet, streams, window=128,
                              compact_every=32)
        snap = chaos.snapshot()
        assert snap["fired"].get("sharded_count") == 1
        assert snap["fired"].get("compactor_build") == 1
        assert snap["fired"].get("place_base") == 1
        m = fleet.metrics.snapshot()
        assert m["reshard_events"]["value"] >= 1
        assert m["fleet_compact_aborts"]["value"] == 1
        # healed mesh shrank to the survivor
        assert fleet.shards == 1
        for t in streams:
            assert fleet.wins2(t) == singles[t]._wins2, t
            assert fleet.auc(t) == singles[t].auc(), t

    def test_heal_preserves_scores(self):
        chaos = FaultInjector.from_spec({"faults": [
            {"point": "sharded_count", "on_call": 2, "action": "error",
             "dropped": [0]}]})
        streams = _tenant_streams(3, 120, seed=12)
        fleet = TenantFleetIndex(compact_every=16, shards=2,
                                 chaos=chaos)
        singles = _drive_pair(fleet, streams, compact_every=16)
        q = np.linspace(-1, 1, 9)
        ranks = fleet.apply_scores([(t, q) for t in streams])
        for i, t in enumerate(streams):
            np.testing.assert_array_equal(
                ranks[i], singles[t].score_batch(q))


class TestAdmissionControl:
    def test_tenant_cap_typed(self):
        with MultiTenantEngine(
                ServingConfig(),
                TenancyConfig(max_tenants=2)) as eng:
            eng.insert("a", 1.0, 1).result(10.0)
            eng.insert("b", 0.5, 0).result(10.0)
            with pytest.raises(TenantRejectedError) as ei:
                eng.insert("c", 0.1, 1)
            assert ei.value.tenant == "c"
            assert "c" in str(ei.value)
            m = eng.metrics.snapshot()
            assert m["tenant_rejected_total"]["value"] == 1
            assert m["tenant_rejected_total{tenant=c}"]["value"] == 1

    def test_tenant_quota_typed(self):
        with MultiTenantEngine(
                ServingConfig(max_batch=4, flush_timeout_s=0.2),
                TenancyConfig(tenant_quota=3)) as eng:
            futs = []
            rejected = 0
            for i in range(40):
                try:
                    futs.append(eng.insert("flood", float(i), i % 2))
                except TenantRejectedError as e:
                    assert e.tenant == "flood"
                    rejected += 1
            assert rejected > 0
            for f in futs:
                f.result(10.0)

    def test_poison_rejected_with_tenant(self):
        with MultiTenantEngine(ServingConfig()) as eng:
            with pytest.raises(PoisonEventError, match="tenant=bad"):
                eng.insert("bad", float("nan"), 1)
            assert eng.metrics.snapshot()["poison_rejects"]["value"] == 1

    def test_closed_engine_attributes_tenant(self):
        eng = MultiTenantEngine(ServingConfig())
        eng.close()
        with pytest.raises(EngineClosedError) as ei:
            eng.insert("zoe", 1.0, 1)
        assert ei.value.tenant == "zoe"


class TestFairScheduling:
    def test_drr_round_robin_order(self):
        """The drain interleaves tenants by weight — a flood cannot
        starve a light tenant (unit test on the drain itself)."""
        eng = MultiTenantEngine(ServingConfig(),
                                TenancyConfig(weight=2))
        eng.close()     # park the worker; exercise the drain directly
        from tuplewise_tpu.serving.tenancy import _FleetRequest

        with eng._cv:
            import collections as c

            eng._pending = {
                "heavy": c.deque(_FleetRequest("insert", "heavy",
                                               np.ones(1), np.ones(1))
                                 for _ in range(6)),
                "light": c.deque(_FleetRequest("insert", "light",
                                               np.ones(1), np.ones(1))
                                 for _ in range(2)),
            }
            eng._rotation = ["heavy", "light"]
            eng._n_pending = 8
            batch = eng._drr_take(8)
        assert [r.tenant for r in batch] == [
            "heavy", "heavy", "light", "light", "heavy", "heavy",
            "heavy", "heavy"]

    def test_light_tenant_served_alongside_flood(self):
        with MultiTenantEngine(
                ServingConfig(max_batch=8, flush_timeout_s=0.01,
                              queue_size=4096),
                TenancyConfig(weight=2, tenant_quota=4096)) as eng:
            heavy = [eng.insert("heavy", float(i), i % 2)
                     for i in range(200)]
            light = eng.insert("light", 0.5, 1)
            light.result(5.0)   # must NOT wait for the whole flood
            for f in heavy:
                f.result(10.0)
            assert eng.tenant_stats("light")["n_events"] == 1


class TestTenantLifecycle:
    def test_idle_eviction(self):
        with MultiTenantEngine(
                ServingConfig(max_batch=8, flush_timeout_s=0.001),
                TenancyConfig(idle_evict_s=0.15)) as eng:
            eng.insert("old", 1.0, 1).result(5.0)
            deadline = time.monotonic() + 5.0
            while eng.fleet.has("old") and time.monotonic() < deadline:
                # keep the batcher turning; "fresh" stays active
                eng.insert("fresh", 0.5, 0).result(5.0)
                time.sleep(0.05)
            assert not eng.fleet.has("old")
            assert eng.fleet.has("fresh")
            m = eng.metrics.snapshot()
            assert m["tenants_evicted_total"]["value"] >= 1
            # an evicted tenant re-creates cleanly on its next request
            eng.insert("old", 2.0, 1).result(5.0)
            assert eng.tenant_stats("old")["n_events"] == 1

    def test_slot_reuse_after_drop(self):
        fleet = TenantFleetIndex(compact_every=8)
        streams = _tenant_streams(3, 60, seed=13)
        _drive_pair(fleet, streams, compact_every=8)
        assert fleet.drop("t1")
        assert not fleet.has("t1")
        # the freed slot is reused and the stale row never leaks into
        # the new tenant's counts
        s, l = _tenant_streams(1, 80, seed=14)["t0"]
        fleet.apply_inserts([("newbie", s, l)])
        ref = ExactAucIndex(compact_every=8, engine="jax")
        ref.insert_batch(s, l)
        assert fleet.wins2("newbie") == ref._wins2
        assert fleet.auc("newbie") == ref.auc()

    def test_flight_events(self):
        from tuplewise_tpu.obs.flight import FlightRecorder

        fr = FlightRecorder(capacity=64)
        fleet = TenantFleetIndex(flight=fr)
        fleet.create("a")
        fleet.drop("a")
        counts = fr.counts()
        assert counts.get("tenant_created") == 1
        assert counts.get("tenant_evicted") == 1


class TestCloseAttribution:
    """[ISSUE 8 satellite bugfix] close() must fail pending per-tenant
    futures with the tenant id in the error."""

    def test_micro_batch_engine_close_names_tenant(self):
        # hold the batcher in an injected delay so two tenant-tagged
        # requests are provably queued when close() lands
        chaos = FaultInjector.from_spec({"faults": [
            {"point": "batcher", "on_call": 1, "action": "delay",
             "seconds": 0.8}]})
        eng = MicroBatchEngine(ServingConfig(), chaos=chaos)
        f1 = eng.insert(1.0, 1, tenant="alice")
        f2 = eng.insert(0.5, 0, tenant="bob")
        eng.close()
        for f, tid in ((f1, "alice"), (f2, "bob")):
            with pytest.raises(EngineClosedError) as ei:
                f.result(5.0)
            assert ei.value.tenant == tid
            assert f"tenant={tid}" in str(ei.value)

    def test_untagged_requests_keep_plain_error(self):
        chaos = FaultInjector.from_spec({"faults": [
            {"point": "batcher", "on_call": 1, "action": "delay",
             "seconds": 0.8}]})
        eng = MicroBatchEngine(ServingConfig(), chaos=chaos)
        f = eng.insert(1.0, 1)
        eng.close()
        with pytest.raises(EngineClosedError) as ei:
            f.result(5.0)
        assert ei.value.tenant is None
        assert "tenant=" not in str(ei.value)

    def test_fleet_close_names_tenants(self):
        chaos = FaultInjector.from_spec({"faults": [
            {"point": "batcher", "on_call": 1, "action": "delay",
             "seconds": 0.8}]})
        eng = MultiTenantEngine(ServingConfig(), chaos=chaos)
        f1 = eng.insert("u1", 1.0, 1)
        f2 = eng.insert("u2", 0.5, 0)
        eng.close()
        seen = set()
        for f in (f1, f2):
            with pytest.raises(EngineClosedError) as ei:
                f.result(5.0)
            seen.add(ei.value.tenant)
            assert f"tenant={ei.value.tenant}" in str(ei.value)
        assert seen == {"u1", "u2"}


class TestFleetRecovery:
    """[ISSUE 8] Per-tenant WAL namespacing + snapshot/recover:
    SIGKILL-bit-identical per tenant."""

    def _fill(self, eng, n=240, seed=21):
        rng = np.random.default_rng(seed)
        for i in range(n):
            eng.insert(f"u{i % 3}", rng.standard_normal(2),
                       rng.random(2) < 0.5).result(10.0)

    def test_snapshot_roundtrip_bit_identical(self, tmp_path):
        cfg = ServingConfig(window=100, compact_every=32,
                            snapshot_dir=str(tmp_path / "d"),
                            snapshot_every=90)
        with MultiTenantEngine(cfg) as eng:
            self._fill(eng)
            eng.flush()
            ref = {t: (eng.fleet.wins2(t),
                       eng.tenant_stats(t)["estimate_incomplete"])
                   for t in eng.fleet.tenants()}
        with MultiTenantEngine(cfg, recover=True) as eng2:
            got = {t: (eng2.fleet.wins2(t),
                       eng2.tenant_stats(t)["estimate_incomplete"])
                   for t in eng2.fleet.tenants()}
        assert ref == got

    def test_crash_recovers_from_wal_tail(self, tmp_path):
        """Abandon the engine WITHOUT a graceful close (the in-process
        SIGKILL stand-in): snapshot + tenant-tagged WAL tail must
        rebuild every tenant bit-identically."""
        cfg = ServingConfig(compact_every=16,
                            snapshot_dir=str(tmp_path / "d"),
                            snapshot_every=100)
        eng = MultiTenantEngine(cfg)
        self._fill(eng, n=170, seed=22)
        eng.flush()
        ref = {t: eng.fleet.wins2(t) for t in eng.fleet.tenants()}
        # park the worker without checkpoint_and_close: the WAL was
        # flushed per batch, the last snapshot may be stale — exactly
        # the post-SIGKILL disk state
        eng._closed = True
        eng._worker.join(timeout=10.0)
        with MultiTenantEngine(cfg, recover=True) as eng2:
            got = {t: eng2.fleet.wins2(t)
                   for t in eng2.fleet.tenants()}
        assert ref == got

    def test_wal_records_carry_tenant(self, tmp_path):
        from tuplewise_tpu.serving.recovery import EventLog

        cfg = ServingConfig(snapshot_dir=str(tmp_path / "d"),
                            snapshot_every=10_000)
        with MultiTenantEngine(cfg) as eng:
            eng.insert("alpha", 1.0, 1).result(10.0)
            eng.insert("beta", 0.5, 0).result(10.0)
            eng.flush()
            # read the live log BEFORE close (the graceful-close
            # snapshot prunes it — that is its job)
            recs = list(EventLog.replay_all_records(
                str(tmp_path / "d" / "events.wal")))
        tenants = {r.get("t") for r in recs}
        assert tenants == {"alpha", "beta"}

    def test_capture_includes_every_tenant(self, tmp_path):
        cfg = ServingConfig(snapshot_dir=str(tmp_path / "d"),
                            snapshot_every=10_000)
        with MultiTenantEngine(cfg) as eng:
            self._fill(eng, n=60, seed=23)
            eng.flush()
            extra, meta = capture_fleet_snapshot_state(eng)
            assert sorted(meta["tenants"]) == ["u0", "u1", "u2"]
            assert len(meta["wins2"]) == 3
            for i in range(3):
                assert f"t{i}_pos_base" in extra
                assert f"t{i}_rpos_items" in extra

    def test_sigkill_fleet_recovers(self, tmp_path):
        """The real thing, fleet edition: SIGKILL a multi-tenant serve
        process mid-stream, --recover, finish — every tenant's final
        AUC bit-identical to the uninterrupted reference."""
        d = str(tmp_path / "rk")
        rng = np.random.default_rng(31)
        events = [(f"u{i % 2}", float(rng.standard_normal()
                                      + 0.8 * (i % 3 == 0)),
                   int(i % 3 == 0)) for i in range(240)]
        lines = [json.dumps({"op": "insert", "tenant": t, "score": s,
                             "label": b}) for t, s, b in events]
        args = [sys.executable, "-m", "tuplewise_tpu.harness.cli",
                "serve", "--max-tenants", "8", "--policy", "block",
                "--snapshot-dir", d, "--snapshot-every", "60",
                "--compact-every", "32"]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        p1 = subprocess.Popen(args, stdin=subprocess.PIPE,
                              stdout=subprocess.PIPE, text=True,
                              env=env, cwd=repo)
        for ln in lines[:150]:
            p1.stdin.write(ln + "\n")
        p1.stdin.flush()
        for _ in range(150):
            assert json.loads(p1.stdout.readline())["ok"]
        os.kill(p1.pid, signal.SIGKILL)
        p1.wait(timeout=30)

        feed = lines[150:] + [
            json.dumps({"op": "query", "tenant": t})
            for t in ("u0", "u1")]
        p2 = subprocess.Popen(args + ["--recover"],
                              stdin=subprocess.PIPE,
                              stdout=subprocess.PIPE, text=True,
                              env=env, cwd=repo)
        out, _ = p2.communicate("\n".join(feed) + "\n", timeout=180)
        resp = [json.loads(ln) for ln in out.strip().splitlines()]
        assert all(r["ok"] for r in resp)
        got = {r["tenant"]: r["auc_exact"] for r in resp
               if "auc_exact" in r}

        ref = TenantFleetIndex(compact_every=32)
        for t, s, b in events:
            ref.apply_inserts([(t, [s], [b])])
        assert got == {"u0": ref.auc("u0"), "u1": ref.auc("u1")}

    def test_tenant_streams_deterministic_seeds(self):
        assert tenant_seed(0, "a") != tenant_seed(0, "b")
        assert tenant_seed(0, "a") == tenant_seed(0, "a")
        assert tenant_seed(1, "a") != tenant_seed(0, "a")

    def test_manager_is_subclass_seam(self, tmp_path):
        mgr = FleetRecoveryManager(str(tmp_path / "x"))
        from tuplewise_tpu.serving.recovery import RecoveryManager

        assert isinstance(mgr, RecoveryManager)


class TestReplayFleet:
    def test_zipf_stream_shape(self):
        scores, labels, tenants = make_tenant_stream(2000, 8, skew=1.2,
                                                     seed=5)
        assert len(scores) == len(labels) == len(tenants) == 2000
        counts = {t: int((tenants == t).sum())
                  for t in np.unique(tenants)}
        assert counts["t0"] > counts[max(counts)]   # head is hottest
        _, _, uni = make_tenant_stream(2000, 8, skew=0.0, seed=5)
        assert len(np.unique(uni)) == 8

    def test_record_contract_and_parity(self):
        scores, labels, tenants = make_tenant_stream(1200, 6, seed=6)
        rec = replay_fleet(
            scores, labels, tenants,
            config=ServingConfig(window=200, compact_every=64,
                                 max_batch=64, policy="block",
                                 flush_timeout_s=0.001),
            chunk=3, max_inflight=64)
        assert rec["events_applied"] == 1200
        assert rec["n_tenants"] == 6
        assert rec["tenant_auc_max_abs_err"] < 1e-6
        assert 0 < rec["fleet_count_calls"] <= rec["batches"]
        assert rec["admission"]["tenants_created_total"] == 6
        assert set(rec["tenant_insert_p99_ms"]) == {
            f"t{k}" for k in range(6)}
        assert rec["report"]["tenancy"]["tenants_live"] == 6

    def test_wildcard_slo_block(self):
        scores, labels, tenants = make_tenant_stream(400, 4, seed=7)
        rec = replay_fleet(
            scores, labels, tenants,
            config=ServingConfig(max_batch=64, policy="block",
                                 flush_timeout_s=0.001),
            slo_spec={"objectives": [
                {"name": "tenant_p99", "type": "latency",
                 "metric": "insert_latency_s{tenant=*}",
                 "quantile": "p99", "threshold_ms": 60_000}]})
        slo = rec["slo"]
        assert slo["healthy"]
        assert len(slo["objectives"]["tenant_p99"]["last"][
            "series"]) == 4


class TestDoctorTenantBreakdown:
    def test_breakdown_from_metrics_rows(self):
        from tuplewise_tpu.obs.doctor import tenant_breakdown
        from tuplewise_tpu.utils.profiling import MetricsRegistry

        reg = MetricsRegistry()
        for t, lat in (("a", 0.002), ("b", 0.05)):
            h = reg.histogram("insert_latency_s", labels={"tenant": t})
            for _ in range(4):
                h.observe(lat)
        reg.counter("tenant_rejected_total",
                    labels={"tenant": "b"}).inc(2)
        reg.gauge("slo_breached",
                  labels={"objective": "p99", "tenant": "b"}).set(1.0)
        rows = [{"ts_mono": 1.0, "metrics": reg.snapshot()}]
        out = tenant_breakdown(rows)
        assert out["b"]["rejected"] == 2
        assert out["b"]["slo_breached"] == ["p99"]
        assert out["a"]["insert_p99_ms"] == pytest.approx(2.0)
        assert tenant_breakdown([{"ts_mono": 1.0, "metrics": {}}]) \
            is None
