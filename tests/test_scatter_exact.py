"""Exact O(n d) scatter closed form (ops.scatter_exact) [VERDICT r3
next #7]: must match the streamed tile reduction bit-tightly on every
mask/id configuration the library produces, including swr duplicate
ids (where equal ids mean IDENTICAL rows by the id discipline)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tuplewise_tpu.ops.kernels import Kernel, scatter_kernel
from tuplewise_tpu.ops.pair_tiles import pair_stats
from tuplewise_tpu.ops.scatter_exact import (
    is_builtin_scatter, scatter_pair_stats,
)


class TestScatterClosedForm:
    def test_two_sample_masked_parity(self):
        rng = np.random.default_rng(0)
        A = jnp.asarray(rng.normal(size=(137, 7)).astype(np.float32))
        B = jnp.asarray(rng.normal(size=(90, 7)).astype(np.float32))
        ma = jnp.asarray((rng.random(137) > 0.2).astype(np.float32))
        mb = jnp.asarray((rng.random(90) > 0.3).astype(np.float32))
        se, ce = scatter_pair_stats(A, B, ma, mb)
        sx, cx = pair_stats(scatter_kernel, A, B, mask_a=ma, mask_b=mb,
                            tile_a=32, tile_b=32)
        assert float(se) == pytest.approx(float(sx), rel=1e-5)
        assert float(ce) == float(cx)

    def test_one_sample_swr_duplicate_ids(self):
        """Duplicate ids (swr resampling) reference identical rows;
        the dup-count sort must reproduce pair_stats' id exclusion."""
        rng = np.random.default_rng(1)
        ids = jnp.asarray(rng.integers(0, 60, 137), jnp.int32)
        base = jnp.asarray(rng.normal(size=(60, 7)).astype(np.float32))
        A = base[ids]
        ma = jnp.asarray((rng.random(137) > 0.2).astype(np.float32))
        se, ce = scatter_pair_stats(A, A, ma, ma, ids, ids)
        sx, cx = pair_stats(scatter_kernel, A, A, mask_a=ma, mask_b=ma,
                            ids_a=ids, ids_b=ids, tile_a=32, tile_b=32)
        assert float(se) == pytest.approx(float(sx), rel=1e-5)
        assert float(ce) == float(cx)

    def test_one_sample_distinct_ids_vmaps(self):
        """The local-average worker path vmaps the closed form over
        blocks (incl. the dup-count sort)."""
        rng = np.random.default_rng(2)
        A = jnp.asarray(rng.normal(size=(4, 50, 5)).astype(np.float32))
        ids = jnp.asarray(rng.integers(0, 30, (4, 50)), jnp.int32)
        se, ce = jax.vmap(
            lambda a, i: scatter_pair_stats(a, a, ids_a=i, ids_b=i)
        )(A, ids)
        for w in range(4):
            sx, cx = pair_stats(
                scatter_kernel, A[w], A[w], ids_a=ids[w], ids_b=ids[w],
                tile_a=16, tile_b=16,
            )
            # sums differ where equal-id rows differ (random rows here),
            # so only audit the count identity, which is row-agnostic
            assert float(ce[w]) == float(cx)

    def test_identity_dispatch(self):
        assert is_builtin_scatter(scatter_kernel)
        shadow = Kernel(name="scatter", degree=2, two_sample=False,
                        kind="pair",
                        pair_fn=lambda a, b, xp: xp.zeros(
                            (a.shape[0], b.shape[0])))
        assert not is_builtin_scatter(shadow)

    def test_backend_estimates_unchanged(self):
        """The jax backend's scatter estimates (now closed-form) must
        match the numpy oracle exactly."""
        from tuplewise_tpu import Estimator
        from tuplewise_tpu.data import make_gaussians

        X, _ = make_gaussians(300, 10, dim=4, separation=1.0, seed=3)
        ref = Estimator("scatter", backend="numpy",
                        n_workers=4).complete(X)
        got = Estimator("scatter", backend="jax",
                        n_workers=4).complete(X)
        assert got == pytest.approx(ref, rel=1e-5)
        ref_l = Estimator("scatter", backend="numpy",
                          n_workers=4).local_average(X, seed=0)
        got_l = Estimator("scatter", backend="jax",
                          n_workers=4).local_average(X, seed=0)
        # different PRNGs draw different partitions; statistical check
        assert abs(got_l - ref_l) < 0.2
