"""Import hygiene: the NumPy oracle path must not pull in jax
[tuplewise_tpu/backends/base.py docstring invariant].

This environment preloads jax at interpreter startup, so checking
``'jax' in sys.modules`` is meaningless — instead we evict it and block
re-import before exercising the numpy path.
"""

import subprocess
import sys

_CODE = """
import sys
# evict any preloaded jax, then make importing it an error
for m in [m for m in sys.modules if m == 'jax' or m.startswith('jax.') or m == 'jaxlib' or m.startswith('jaxlib.')]:
    del sys.modules[m]

class _Block:
    def find_spec(self, name, path=None, target=None):
        if name == 'jax' or name.startswith('jax.') or name.startswith('jaxlib'):
            raise ImportError(f'jax import blocked in numpy-only test ({name})')
        return None

sys.meta_path.insert(0, _Block())

import numpy as np
from tuplewise_tpu import Estimator
e = Estimator('auc', backend='numpy', n_workers=2)
# pairs i>j-0.5 always when i>=j, i.e. 15 of 25 ordered pairs -> 0.6
assert abs(e.complete(np.arange(5.0), np.arange(5.0) - 0.5) - 0.6) < 1e-12
e.local_average(np.arange(8.0), np.arange(8.0), seed=0)
e.incomplete(np.arange(8.0), np.arange(8.0), n_pairs=10, seed=0)
print('OK')
"""


def test_numpy_path_does_not_import_jax():
    proc = subprocess.run(
        [sys.executable, "-c", _CODE], capture_output=True, text=True
    )
    assert proc.returncode == 0 and "OK" in proc.stdout, proc.stderr


def test_results_io_quick_rule():
    """The shared quick-sibling rule round-trips (three suites rely on
    it agreeing with itself)."""
    from tuplewise_tpu.utils.results_io import (
        is_quick, quick_sibling, strip_quick,
    )

    assert quick_sibling("a.jsonl", False) == "a.jsonl"
    assert quick_sibling("a.jsonl", True) == "a_quick.jsonl"
    assert quick_sibling("trace_dir", True) == "trace_dir_quick"
    assert strip_quick("a_quick.jsonl") == "a.jsonl"
    assert strip_quick("a.jsonl") == "a.jsonl"
    assert is_quick("a_quick.jsonl") and not is_quick("a.jsonl")
    # round trip: sibling of a base name strips back to itself
    for name in ("x.jsonl", "tradeoff_rounds_N125000.jsonl", "d"):
        assert strip_quick(quick_sibling(name, True)) == name
